"""Pipeline runtime (replaces the GStreamer core): elements, pads,
negotiation, push scheduling, events, bus, pipeline parser."""

from .element import (
    Element,
    NegotiationError,
    Pad,
    PadDirection,
    SinkElement,
    SourceElement,
    StreamError,
    TransformElement,
)
from .events import Event, EventKind, Message, MessageKind
from .pipeline import Bus, Pipeline
from .registry import element_factory, list_elements, make, register_element
from .parser import CapsFilter, ParseError, parse_caps_string, parse_launch
from .serving import MODEL_POOL, ModelPool, PoolConflictError, SharedBatcher
from .lifecycle import LifecycleError, ModelVersion, VersionManager

__all__ = [
    "Element", "NegotiationError", "Pad", "PadDirection", "SinkElement",
    "SourceElement", "StreamError", "TransformElement",
    "Event", "EventKind", "Message", "MessageKind",
    "Bus", "Pipeline",
    "element_factory", "list_elements", "make", "register_element",
    "CapsFilter", "ParseError", "parse_caps_string", "parse_launch",
    "MODEL_POOL", "ModelPool", "PoolConflictError", "SharedBatcher",
    "LifecycleError", "ModelVersion", "VersionManager",
]

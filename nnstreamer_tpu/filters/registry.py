"""Filter framework registry + auto-detection.

Parity targets:
- name→framework registry: nnstreamer_filter_probe/find
  (/root/reference/gst/nnstreamer/nnstreamer_subplugin.c:141,225)
- framework auto-detection from model file extension with conf-driven
  priority: gst_tensor_filter_detect_framework
  (/root/reference/gst/nnstreamer/tensor_filter/tensor_filter_common.c:1224,
  _detect_framework_from_config :1177)
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Type

from .api import FilterSubplugin

_lock = threading.Lock()
_frameworks: Dict[str, Type[FilterSubplugin]] = {}

# extension → ordered framework candidates (overridable via conf, parity:
# framework_priority_* keys in nnstreamer.ini.in)
_EXT_DEFAULTS: Dict[str, list] = {
    ".stablehlo": ["jax-xla"],
    ".mlir": ["jax-xla"],
    ".jaxexp": ["jax-xla"],
    ".pkl": ["jax-xla"],
    ".msgpack": ["jax-xla"],
    ".py": ["python3"],
    ".tflite": ["tensorflow-lite"],
    ".onnx": ["onnx"],
    ".pb": ["tensorflow"],
    ".pt": ["pytorch"],
    ".pth": ["pytorch"],
    ".npz": ["jax-xla"],
    ".safetensors": ["jax-xla"],
}


def register_filter(cls: Type[FilterSubplugin]) -> Type[FilterSubplugin]:
    """Class decorator (parity: nnstreamer_filter_probe self-registration)."""
    if not cls.NAME:
        raise ValueError(f"{cls.__name__} has empty NAME")
    with _lock:
        _frameworks[cls.NAME] = cls
    return cls


def find_filter(name: str) -> Type[FilterSubplugin]:
    _ensure_builtin()
    with _lock:
        try:
            return _frameworks[name]
        except KeyError:
            known = ", ".join(sorted(_frameworks))
            raise KeyError(
                f"no filter framework {name!r}; known: {known}") from None


def list_filters():
    _ensure_builtin()
    with _lock:
        return sorted(_frameworks)


def detect_framework(model) -> str:
    """framework="auto": choose by model extension + conf priority."""
    _ensure_builtin()
    path = model[0] if isinstance(model, (list, tuple)) else model
    if callable(path):
        return "custom-easy"
    if not isinstance(path, (str, os.PathLike)):
        raise ValueError(f"cannot auto-detect framework for {type(path)}")
    ext = os.path.splitext(str(path))[1].lower()
    from ..utils.conf import get_conf

    candidates = get_conf().framework_priority(ext) or \
        _EXT_DEFAULTS.get(ext, [])
    with _lock:
        for c in candidates:
            if c in _frameworks:
                return c
    # in-process registered model name?
    from .custom import easy_model_registered
    from .jax_xla import get_model

    if isinstance(path, str):
        if get_model(path) is not None:
            return "jax-xla"
        if easy_model_registered(path):
            return "custom-easy"
    raise ValueError(
        f"cannot auto-detect framework for model {path!r} (ext {ext!r})")


_builtin_done = False
_builtin_lock = threading.Lock()


def _ensure_builtin() -> None:
    global _builtin_done
    if _builtin_done:
        return
    with _builtin_lock:
        if _builtin_done:
            return
        from . import (  # noqa: F401  self-registering
            custom,
            jax_xla,
            onnx,
            pytorch,
            tensorflow,
            tflite,
        )

        _builtin_done = True

#!/usr/bin/env python
"""Real pretrained models through the importer frameworks.

Runs three reference models end to end — no TF runtime, no interpreter:

  - mobilenet_v2 quant .tflite  → classifies orange.raw   → "orange"
  - mnist frozen .pb            → reads the digit image   → "9"
  - conv_actions frozen .pb     → hears yes.wav           → "yes"

    python examples/pretrained_imports.py

Requires the reference test assets (skips politely when absent).
"""

import os
import sys

try:
    import nnstreamer_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REF = "/root/reference/tests/test_models"
COMMANDS = ["_silence_", "_unknown_", "yes", "no", "up", "down", "left",
            "right", "on", "off", "stop", "go"]


def main() -> int:
    from nnstreamer_tpu.elements.filter import FilterSingle
    from nnstreamer_tpu.core import TensorsSpec

    if not os.path.isdir(REF):
        print("reference assets not present — nothing to demo")
        return 0

    # 1) tflite: quantized MobileNetV2 classifier
    img = np.fromfile(os.path.join(REF, "data", "orange.raw"),
                      np.uint8).reshape(1, 224, 224, 3)
    fs = FilterSingle(
        framework="tensorflow-lite",
        model=os.path.join(REF, "models",
                           "mobilenet_v2_1.0_224_quant.tflite"))
    labels = [ln.strip() for ln in open(
        os.path.join(REF, "labels", "labels.txt"))]
    logits = np.asarray(fs.invoke([img])[0])[0]  # this graph ends at logits
    e = np.exp(logits - logits.max())
    probs = e / e.sum()
    print(f"tflite mobilenet_v2:  {labels[int(probs.argmax())]!r} "
          f"(p={probs.max():.2f})")

    # 2) frozen GraphDef: MNIST linear classifier
    digit = np.fromfile(os.path.join(REF, "data", "9.raw"),
                        np.uint8).astype(np.float32) / 255.0
    fs = FilterSingle(
        framework="tensorflow",
        model=os.path.join(REF, "models", "mnist.pb"),
        input_spec=TensorsSpec.parse("784:1", "float32"))
    probs = np.asarray(fs.invoke([digit.reshape(1, 784)])[0])[0]
    print(f"tensorflow mnist:     digit {int(probs.argmax())} "
          f"(p={probs.max():.2f})")

    # 3) frozen GraphDef: speech commands (WAV → spectrogram → Mfcc →
    #    convnet, the audio front end reimplemented for XLA)
    from nnstreamer_tpu.filters.tf_import import decode_wav_bytes

    pcm, _ = decode_wav_bytes(
        open(os.path.join(REF, "data", "yes.wav"), "rb").read(),
        desired_samples=16000, desired_channels=1)
    fs = FilterSingle(
        framework="tensorflow",
        model=os.path.join(REF, "models", "conv_actions_frozen.pb"))
    probs = np.asarray(fs.invoke([pcm])[0]).ravel()
    print(f"tensorflow speech:    {COMMANDS[int(probs.argmax())]!r} "
          f"(p={probs.max():.2f})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Endurance + teardown stress for the threaded runtime (round-3
verdict #6).

The runtime replaces GStreamer's decades-hardened scheduler with a
compact thread/CV push graph (runtime/element.py, elements/basic.py);
these tests are the stand-in for that maturity gap plus the reference's
valgrind tooling (/root/reference/tools/debugging/valgrind_suppression):
a deep pipeline streams 50k buffers while thread/fd counts stay flat and
RSS stays bounded, and a queue/tee/repo topology survives 100
start/stop cycles without leaking threads or descriptors.
"""

import os
import threading

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.filters.custom import register_custom_easy
from nnstreamer_tpu.runtime import parse_launch

SOAK_BUFFERS = int(os.environ.get("NNS_SOAK_BUFFERS", "50000"))


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _threads() -> int:
    return threading.active_count()


class TestSoak:
    def test_50k_buffers_deep_pipeline_stable(self):
        """appsrc → transform → queue → tee → custom filter → sinks,
        ≥50k buffers: every buffer arrives; thread and fd counts are
        flat; RSS growth from 10% in to the end stays bounded."""
        spec = TensorsSpec.parse("8", "float32")
        register_custom_easy(
            "soak_scale", lambda xs: [xs[0] * 2.0],
            in_spec=spec, out_spec=spec)
        # no XLA elements: the soak exercises the RUNTIME (threads,
        # queues, pads) hermetically — device throughput is bench.py's
        # job, and a tunneled device would turn 50k buffers into hours
        p = parse_launch(
            "appsrc name=src max_buffers=256 ! "
            "tensor_filter framework=custom-easy model=soak_scale ! "
            "queue max_size_buffers=256 ! tee name=t "
            "t. ! tensor_filter framework=custom-easy model=soak_scale ! "
            "tensor_sink name=sink_a "
            "t. ! tensor_sink name=sink_b")
        src = p["src"]
        src.spec = spec
        x = np.arange(8, dtype=np.float32)
        early = min(max(SOAK_BUFFERS // 10, 1), SOAK_BUFFERS - 1)
        late = min(max(SOAK_BUFFERS * 9 // 10, early + 1),
                   SOAK_BUFFERS - 1)
        if late == early:  # tiny smoke-run values: one probe is enough
            early = late = 0
        base_threads = _threads()
        stats = {}
        with p:
            for i in range(SOAK_BUFFERS):
                src.push_buffer(Buffer.of(x, pts=i))
                if i in (early, late):  # mid-stream steady-state probes
                    stats[i] = (_rss_kb(), _threads(), _fd_count())
            src.end_of_stream()
            assert p.wait_eos(timeout=600), "soak pipeline stalled"
            rendered_a = p["sink_a"].buffers_rendered
            rendered_b = p["sink_b"].buffers_rendered
        assert rendered_a == SOAK_BUFFERS, rendered_a
        assert rendered_b == SOAK_BUFFERS, rendered_b
        (rss_e, thr_e, fds_e), (rss_l, thr_l, fds_l) = \
            stats[early], stats[late]
        # thread/fd population must be flat across the steady state
        assert thr_l == thr_e, (thr_e, thr_l)
        assert abs(fds_l - fds_e) <= 4, (fds_e, fds_l)
        # bounded RSS: allow modest allocator noise, catch per-buffer
        # leaks (50k buffers × even 1 KB leaked = +45 MB would fail)
        growth_kb = rss_l - rss_e
        assert growth_kb < 40_000, f"RSS grew {growth_kb} KB during soak"
        # teardown: every pipeline thread joined
        assert _threads() <= base_threads, (base_threads, _threads())

    def test_sustained_flexible_and_meta_traffic(self):
        """10k flexible buffers (per-buffer schema + meta dict) — the
        paths with per-buffer allocations — stay leak-free."""
        spec = TensorsSpec.parse("4", "float32")
        p = parse_launch(
            "appsrc name=src max_buffers=128 ! "
            "queue ! tensor_sink name=out")
        src = p["src"]
        src.spec = spec
        n = 10_000
        with p:
            for i in range(n):
                b = Buffer.of(np.full((4,), i % 17, np.float32), pts=i)
                b.meta["seq"] = i
                src.push_buffer(b)
                if i == n // 10:
                    rss_mid = _rss_kb()
            src.end_of_stream()
            assert p.wait_eos(timeout=300)
            assert p["out"].buffers_rendered == n
            rss_end = _rss_kb()
        assert rss_end - rss_mid < 30_000, (rss_mid, rss_end)


class TestStartStopCycles:
    def test_100_cycles_queue_tee_repo(self):
        """Build/start/run/stop a topology with queue, tee and a repo
        loop 100 times: thread and fd counts return to baseline each
        time (teardown leaks compound across cycles and fail fast)."""
        from nnstreamer_tpu.elements.repo import REPO

        spec = TensorsSpec.parse("1", "float32")
        register_custom_easy(
            "cycle_inc", lambda xs: [xs[0] + 1.0],
            in_spec=spec, out_spec=spec)
        base_threads = _threads()
        base_fds = _fd_count()
        for cycle in range(100):
            REPO.reset()
            p = parse_launch(
                "tensor_reposrc name=loop slot=0 num_buffers=3 "
                "caps=other/tensors,format=static,num_tensors=1,"
                "dimensions=1,types=float32,framerate=0/1 ! "
                "tensor_filter framework=custom-easy model=cycle_inc ! "
                "queue ! tee name=t "
                "t. ! tensor_reposink slot=0 "
                "t. ! tensor_sink name=out")
            with p:
                assert p.wait_eos(timeout=60), f"cycle {cycle} stalled"
                assert p["out"].buffers_rendered == 3
            del p
        # all pipeline threads joined, no fd creep
        assert _threads() == base_threads, (base_threads, _threads())
        assert _fd_count() <= base_fds + 4, (base_fds, _fd_count())

    def test_repeated_edge_server_cycles_release_ports(self):
        """Start/stop a query server+client pair 30 times over inproc:
        the hub must release every binding (round-3 weak #4: fresh
        runtime code needs teardown evidence, not just happy paths)."""
        from nnstreamer_tpu.core import Caps
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc
        from nnstreamer_tpu.runtime import Pipeline
        from nnstreamer_tpu.runtime.registry import make

        spec = TensorsSpec.parse("4", "float32", rate=0)
        register_custom_easy(
            "cycle_id", lambda xs: [xs[0]],
            in_spec=spec, out_spec=spec)
        base_threads = _threads()
        for cycle in range(30):
            sp = Pipeline(name=f"srv{cycle}")
            qsrc = make("tensor_query_serversrc", el_name="qsrc",
                        host="inproc-cycle", port=7123,
                        connect_type="inproc", id=60,
                        caps=Caps.from_spec(spec))
            flt = make("tensor_filter", el_name="f",
                       framework="custom-easy", model="cycle_id")
            qsink = make("tensor_query_serversink", el_name="qsink", id=60)
            sp.add(qsrc, flt, qsink).link(qsrc, flt, qsink)
            with sp:
                cp = Pipeline(name=f"cli{cycle}")
                src = AppSrc(name="src", spec=spec)
                cli = make("tensor_query_client", el_name="cli",
                           host="inproc-cycle", port=7123,
                           connect_type="inproc", timeout=30000)
                snk = AppSink(name="out")
                cp.add(src, cli, snk).link(src, cli, snk)
                with cp:
                    src.push_buffer(Buffer.of(
                        np.full((4,), cycle, np.float32)))
                    src.end_of_stream()
                    assert cp.wait_eos(timeout=30), f"cycle {cycle}"
                    out = snk.pull(timeout=1)
                    assert out is not None
        assert _threads() <= base_threads + 2, (base_threads, _threads())


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v"] + sys.argv[1:]))

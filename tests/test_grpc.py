"""gRPC tensor bridge: localhost round-trips in all four role
combinations (parity model: the reference runs paired pipelines over
localhost, tests/nnstreamer_grpc SSAT).
"""

from fractions import Fraction

import numpy as np
import pytest

pytest.importorskip("grpc")

from nnstreamer_tpu.core import Buffer, TensorsSpec  # noqa: E402
from nnstreamer_tpu.elements.basic import AppSink, AppSrc  # noqa: E402
from nnstreamer_tpu.runtime import Pipeline  # noqa: E402
from nnstreamer_tpu.runtime.registry import make  # noqa: E402


def frames(n=3):
    rng = np.random.default_rng(7)
    return [Buffer.of(rng.standard_normal((2, 4)).astype(np.float32),
                      np.arange(3, dtype=np.int32), pts=i * 100)
            for i in range(n)]


def run_sender(sink_el, bufs):
    p = Pipeline()
    src = AppSrc(name="src", spec=TensorsSpec.parse(
        "4:2,3", "float32,int32", rate=Fraction(30)))
    p.add(src, sink_el).link(src, sink_el)
    p.start()
    for b in bufs:
        src.push_buffer(b)
    return p, src


def run_receiver(src_el, n):
    p = Pipeline()
    sink = AppSink(name="out")
    p.add(src_el, sink).link(src_el, sink)
    p.start()
    got = []
    while len(got) < n:
        b = sink.pull(timeout=20)
        assert b is not None, f"timed out after {len(got)}/{n} buffers"
        got.append(b)
    return p, got


def assert_frames_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.num_tensors == w.num_tensors
        for gt, wt in zip(g.tensors, w.tensors):
            np.testing.assert_array_equal(gt.np(), wt.np())
            assert gt.spec.dtype == wt.spec.dtype


@pytest.mark.parametrize("idl", ["protobuf", "flatbuf", "flexbuf"])
def test_sink_server_src_client(idl):
    """sink serves RecvTensors; src connects and receives the stream."""
    bufs = frames()
    snk = make("tensor_sink_grpc", el_name="gs", server=True, port=0,
               idl=idl)
    p1, src1 = run_sender(snk, [])  # start server first, port auto
    port = snk.bound_port
    gsrc = make("tensor_src_grpc", el_name="gr", server=False, port=port,
                idl=idl, num_buffers=len(bufs))
    p2 = Pipeline()
    sink = AppSink(name="out")
    p2.add(gsrc, sink).link(gsrc, sink)
    p2.start()
    import time
    time.sleep(0.3)  # let the RecvTensors subscription attach
    for b in bufs:
        src1.push_buffer(b)
    got = []
    while len(got) < len(bufs):
        b = sink.pull(timeout=20)
        assert b is not None
        got.append(b)
    assert_frames_equal(got, bufs)
    p2.stop()
    p1.stop()


@pytest.mark.parametrize("idl", ["protobuf"])
def test_src_server_sink_client(idl):
    """src serves SendTensors; sink connects and streams into it."""
    bufs = frames()
    gsrc = make("tensor_src_grpc", el_name="gr", server=True, port=0,
                idl=idl, num_buffers=len(bufs))
    p2 = Pipeline()
    sink = AppSink(name="out")
    p2.add(gsrc, sink).link(gsrc, sink)
    p2.start()
    port = gsrc.bound_port
    snk = make("tensor_sink_grpc", el_name="gs", server=False, port=port,
               idl=idl)
    p1, src1 = run_sender(snk, bufs)
    got = []
    while len(got) < len(bufs):
        b = sink.pull(timeout=20)
        assert b is not None
        got.append(b)
    assert_frames_equal(got, bufs)
    p1.stop()
    p2.stop()


def test_src_stops_cleanly_without_peer():
    gsrc = make("tensor_src_grpc", el_name="gr", server=True, port=0,
                num_buffers=1)
    p = Pipeline()
    sink = AppSink(name="out")
    p.add(gsrc, sink).link(gsrc, sink)
    p.start()
    assert gsrc.bound_port
    p.stop()  # no client ever connected: must not hang or error

"""``tensor_crop`` — data-driven cropping of a raw stream.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_crop.c
(:839): two sink pads — ``sink_raw`` carries the stream, ``sink_info`` a
*flexible* tensor stream of crop regions (x, y, w, h per region, produced
e.g. by the tensor_region decoder) — collected with the time-sync engine;
the output is a flexible stream of cropped patches (one tensor per
region, shapes vary per buffer).

TPU note: each crop is a ``lax.dynamic_slice`` when the raw tensor is
device-resident; patch extraction happens in HBM and only the (small)
crops move on.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorFormat, TensorsSpec
from ..runtime.element import Element, NegotiationError, Pad, StreamError
from ..runtime.events import Event, EventKind
from ..runtime.registry import register_element
from .sync import Collector, SyncPolicy


@register_element("tensor_crop")
class TensorCrop(Element):
    FACTORY = "tensor_crop"

    def __init__(self, name=None, lateness: int = 0,
                 sync_mode: str = "nosync", sync_option: str = "", **props):
        self.lateness = lateness
        self.sync_mode = sync_mode
        self.sync_option = sync_option
        super().__init__(name, **props)
        self.add_sink_pad("sink_raw")
        self.add_sink_pad("sink_info")
        self.add_src_pad()
        self._collector: Optional[Collector] = None

    @property
    def raw_pad(self) -> Pad:
        return self.sinkpads[0]

    @property
    def info_pad(self) -> Pad:
        return self.sinkpads[1]

    def start(self) -> None:
        self._collector = Collector(
            SyncPolicy.parse(self.sync_mode, self.sync_option),
            [p.name for p in self.sinkpads])

    def propose_src_caps(self, pad: Pad) -> Caps:
        raw_spec = self.raw_pad.spec
        rate = raw_spec.rate if raw_spec is not None else 0
        return Caps.from_spec(TensorsSpec(
            format=TensorFormat.FLEXIBLE, rate=rate))

    def chain(self, pad: Pad, buf: Buffer) -> None:
        for bufset in self._collector.deposit(pad.name, buf):
            raw = bufset.get("sink_raw")
            info = bufset.get("sink_info")
            if raw is None or info is None:
                continue
            self.push(self._crop(raw, info))

    def _crop(self, raw: Buffer, info: Buffer) -> Buffer:
        """info tensor: (N, 4) of x, y, w, h (uint32/float), one crop per
        region, over the raw stream's innermost-3 dims (ch:w:h frame)."""
        regions = np.asarray(info.tensors[0].np()).reshape(-1, 4)
        t = raw.tensors[0]
        shape = t.spec.shape  # row-major; frame is (..., h, w, ch)
        if len(shape) < 3:
            raise StreamError(
                f"{self.name}: raw stream must be at least rank 3 "
                f"(h, w, ch); got {shape}")
        h_ax, w_ax = len(shape) - 3, len(shape) - 2
        out: List[Tensor] = []
        dev = t.is_device
        arr = t.jax() if dev else t.np()
        for (x, y, w, hgt) in regions:
            x, y, w, hgt = int(x), int(y), int(w), int(hgt)
            x = max(0, min(x, shape[w_ax] - 1))
            y = max(0, min(y, shape[h_ax] - 1))
            w = max(1, min(w, shape[w_ax] - x))
            hgt = max(1, min(hgt, shape[h_ax] - y))
            sl = [slice(None)] * len(shape)
            sl[h_ax] = slice(y, y + hgt)
            sl[w_ax] = slice(x, x + w)
            out.append(Tensor(arr[tuple(sl)]))
        return Buffer(tensors=out, pts=raw.pts, duration=raw.duration,
                      format=TensorFormat.FLEXIBLE, meta=dict(raw.meta))

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.EOS:
            if self._collector is None or self._collector.mark_eos(pad.name):
                self.forward_event(event)
            return
        super().handle_event(pad, event)

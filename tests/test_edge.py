"""L5 inter-device layer tests: wire codec, query offload (inproc +
localhost TCP), client_id routing across concurrent clients, caps
exchange, and edge pub/sub.

Parity model: the reference tests client+server pipelines in ONE process
over localhost (/root/reference/tests/nnstreamer_edge/query/
unittest_query.cc); these tests mirror that shape.
"""

import queue as _q
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorFormat, TensorsSpec
from nnstreamer_tpu.edge import (
    Envelope,
    MSG_PUBLISH,
    MSG_QUERY,
    EdgeMessage,
    query_server_entry,
)
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.filters.jax_xla import register_model
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SPEC = TensorsSpec.parse("4:1", "float32")


def drain(sink, timeout=0.3):
    out = []
    while True:
        b = sink.pull(timeout=timeout)
        if b is None:
            return out
        out.append(b)


class TestWire:
    def test_roundtrip_buffer(self):
        b = Buffer.of(np.arange(6, dtype=np.float32).reshape(2, 3), pts=123)
        m = EdgeMessage.from_buffer(MSG_QUERY, b, client_id=7, seq=42,
                                    info="t")
        m2 = EdgeMessage.unpack(m.pack())
        assert (m2.mtype, m2.client_id, m2.seq, m2.info) == (
            MSG_QUERY, 7, 42, "t")
        b2 = m2.to_buffer()
        assert b2.pts == 123
        np.testing.assert_array_equal(b2.tensors[0].np(),
                                      b.tensors[0].np())

    def test_roundtrip_no_payload_no_pts(self):
        m = EdgeMessage(mtype=MSG_PUBLISH, info="topic")
        m2 = EdgeMessage.unpack(m.pack())
        assert m2.pts is None and m2.payloads == [] and m2.info == "topic"

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError, match="magic"):
            EdgeMessage.unpack(b"\x00" * 64)


def _model_name(tag):
    name = f"edge_double_{tag}"
    register_model(name, lambda x: x * 2.0, in_shapes=[(1, 4)],
                   in_dtypes=np.float32)
    return name


def _server_pipeline(tag, connect_type, host, port, server_id):
    """serversrc ! tensor_filter(double) ! serversink"""
    p = Pipeline(name=f"server-{tag}")
    src = make("tensor_query_serversrc", el_name="qsrc", host=host,
               port=port, connect_type=connect_type, id=server_id,
               caps=Caps.from_spec(SPEC))
    flt = make("tensor_filter", el_name="f", framework="jax-xla",
               model=_model_name(tag))
    snk = make("tensor_query_serversink", el_name="qsink", id=server_id)
    p.add(src, flt, snk).link(src, flt, snk)
    return p, src


def _client_pipeline(tag, connect_type, host, port):
    """appsrc ! tensor_query_client ! appsink"""
    p = Pipeline(name=f"client-{tag}")
    src = AppSrc(name="src", spec=SPEC)
    # generous timeout: the server's first invoke includes XLA compile,
    # which can exceed 10s on a loaded machine
    cli = make("tensor_query_client", el_name="cli", host=host, port=port,
               connect_type=connect_type, timeout=30000)
    snk = AppSink(name="out")
    p.add(src, cli, snk).link(src, cli, snk)
    return p, src, snk


class TestQueryOffload:
    @pytest.mark.parametrize("connect_type", ["inproc", "tcp"])
    def test_offload_roundtrip(self, connect_type):
        host = "localhost" if connect_type == "tcp" else "inproc-a"
        sp, ssrc = _server_pipeline(connect_type, connect_type, host,
                                    7001 if connect_type == "inproc" else 0,
                                    server_id=10)
        with sp:
            port = ssrc.port  # ephemeral for tcp
            cp, src, snk = _client_pipeline(connect_type, connect_type,
                                            host, port)
            with cp:
                for i in range(5):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i * 10))
                src.end_of_stream()
                assert cp.wait_eos(timeout=30)
                out = drain(snk)
        assert len(out) == 5
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))
            assert b.pts == i * 10  # metadata from the incoming buffer
            assert "client_id" not in b.meta

    def test_client_learns_server_caps(self):
        sp, ssrc = _server_pipeline("caps", "inproc", "inproc-caps", 7002,
                                    server_id=11)
        with sp:
            cp, src, snk = _client_pipeline("caps", "inproc",
                                            "inproc-caps", 7002)
            with cp:
                src.push_buffer(Buffer.of(np.ones((1, 4), np.float32)))
                src.end_of_stream()
                assert cp.wait_eos(timeout=30)
                cli = cp["cli"]
                # src caps came from the serversink registration, so they
                # are the server pipeline's static output caps
                assert cli.srcpad.spec is not None
                assert cli.srcpad.spec.tensors[0].dims == (4, 1)
                drain(snk)

    def test_two_clients_routed_independently(self):
        sp, ssrc = _server_pipeline("rt", "tcp", "localhost", 0,
                                    server_id=12)
        with sp:
            port = ssrc.port
            results = {}

            def run_client(tag, base):
                cp, src, snk = _client_pipeline(tag, "tcp", "localhost",
                                                port)
                with cp:
                    for i in range(4):
                        src.push_buffer(Buffer.of(
                            np.full((1, 4), base + i, np.float32)))
                    src.end_of_stream()
                    assert cp.wait_eos(timeout=30)
                    results[tag] = [float(b.tensors[0].np()[0, 0])
                                    for b in drain(snk)]

            t1 = threading.Thread(target=run_client, args=("c1", 100.0))
            t2 = threading.Thread(target=run_client, args=("c2", 200.0))
            t1.start(); t2.start()
            t1.join(timeout=60); t2.join(timeout=60)
        # each client saw ONLY its own answers, in order
        assert results["c1"] == [200.0 + 2 * i for i in range(4)]
        assert results["c2"] == [400.0 + 2 * i for i in range(4)]

    def test_serversink_metaless_frames_error(self):
        snk = make("tensor_query_serversink", el_name="qs", id=99,
                   metaless_frame_limit=2)
        snk.render(Buffer.of(np.zeros((1,), np.float32)))  # warn + drop
        from nnstreamer_tpu.runtime.element import StreamError

        with pytest.raises(StreamError, match="metaless"):
            snk.render(Buffer.of(np.zeros((1,), np.float32)))


class TestEdgePubSub:
    @pytest.mark.parametrize("connect_type", ["inproc", "tcp"])
    def test_publish_subscribe(self, connect_type):
        host = "localhost" if connect_type == "tcp" else "inproc-pub"
        # publisher: appsrc ! edgesink
        pub = Pipeline(name="pub")
        psrc = AppSrc(name="src", spec=SPEC)
        esink = make("edgesink", el_name="es", host=host,
                     port=7003 if connect_type == "inproc" else 0,
                     connect_type=connect_type, topic="cam0")
        pub.add(psrc, esink).link(psrc, esink)
        pub.start()
        try:
            port = esink.port
            # subscriber: edgesrc ! appsink
            sub = Pipeline(name="sub")
            esrc = make("edgesrc", el_name="er", dest_host=host,
                        dest_port=port, connect_type=connect_type,
                        topic="cam0", caps=Caps.from_spec(SPEC),
                        num_buffers=3)
            ssnk = AppSink(name="out")
            sub.add(esrc, ssnk).link(esrc, ssnk)
            with sub:
                time.sleep(0.2)  # let the subscription register
                for i in range(3):
                    psrc.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32)))
                assert sub.wait_eos(timeout=30)
                got = drain(ssnk)
        finally:
            pub.stop()
        assert [float(b.tensors[0].np()[0, 0]) for b in got] == [0.0, 1.0,
                                                                 2.0]

    def test_topic_mismatch_receives_nothing(self):
        pub = Pipeline(name="pub2")
        psrc = AppSrc(name="src", spec=SPEC)
        esink = make("edgesink", el_name="es", host="inproc-pub2",
                     port=7004, connect_type="inproc", topic="cam0")
        pub.add(psrc, esink).link(psrc, esink)
        pub.start()
        try:
            sub = Pipeline(name="sub2")
            esrc = make("edgesrc", el_name="er", dest_host="inproc-pub2",
                        dest_port=7004, connect_type="inproc",
                        topic="other", caps=Caps.from_spec(SPEC))
            ssnk = AppSink(name="out")
            sub.add(esrc, ssnk).link(esrc, ssnk)
            with sub:
                time.sleep(0.1)
                for i in range(3):
                    psrc.push_buffer(Buffer.of(
                        np.ones((1, 4), np.float32)))
                time.sleep(0.3)
                assert drain(ssnk, timeout=0.1) == []
        finally:
            pub.stop()

    def test_edgesrc_learns_publisher_caps(self):
        pub = Pipeline(name="pub3")
        psrc = AppSrc(name="src", spec=SPEC)
        esink = make("edgesink", el_name="es", host="inproc-pub3",
                     port=7005, connect_type="inproc")
        pub.add(psrc, esink).link(psrc, esink)
        pub.start()
        try:
            esrc = make("edgesrc", el_name="er", dest_host="inproc-pub3",
                        dest_port=7005, connect_type="inproc")
            spec = esrc.output_spec()
            assert spec.is_static()
            assert spec.tensors[0].dims == (4, 1)
            esrc.stop()
        finally:
            pub.stop()


class TestFailover:
    def test_client_fails_over_to_alternate_server(self):
        """Primary unreachable → the alternate-hosts list is walked in
        order (parity: MQTT-hybrid reconnect, tensor_query/README.md)."""
        sp, ssrc = _server_pipeline("fo", "tcp", "localhost", 0,
                                    server_id=42)
        with sp:
            port = ssrc.port
            p = Pipeline(name="client-fo")
            src = AppSrc(name="src", spec=SPEC)
            cli = make("tensor_query_client", el_name="cli",
                       host="127.0.0.1", port=1,  # dead primary
                       connect_type="tcp", timeout=30000,
                       alternate_hosts=f"127.0.0.1:2,localhost:{port}")
            snk = AppSink(name="out")
            p.add(src, cli, snk).link(src, cli, snk)
            with p:
                src.push_buffer(Buffer.of(np.ones((1, 4), np.float32)))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = drain(snk)
            assert cli.connected_addr == ("localhost", port)
        assert len(out) == 1
        np.testing.assert_array_equal(
            out[0].tensors[0].np(), np.full((1, 4), 2.0, np.float32))

    def test_all_servers_dead_raises(self):
        p = Pipeline(name="client-dead")
        src = AppSrc(name="src", spec=SPEC)
        cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
                   port=1, connect_type="tcp",
                   alternate_hosts="127.0.0.1:2")
        snk = AppSink(name="out")
        p.add(src, cli, snk).link(src, cli, snk)
        from nnstreamer_tpu.runtime.element import NegotiationError

        with pytest.raises(NegotiationError, match="no query server"):
            p.start()
        p.stop()

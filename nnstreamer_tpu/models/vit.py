"""Vision Transformer for the model zoo.

The reference ships CNN-era test models only; this family extends the
zoo with the attention-based architecture class and is the in-tree user
of the Pallas flash-attention kernel (``ops.flash_attention``) — patch
sequences are exactly the workload the blockwise kernel and the ring
attention sequence-parallel path (parallel/collectives.py) exist for.

Functional pytree style matching models/mobilenet.py: ``vit_init`` →
params dict, ``vit_apply(params, x)`` jittable, bf16 compute with f32
accumulation, ``register_vit`` exposes it to ``tensor_filter``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import jax
    import jax.numpy as jnp
except ImportError:  # pragma: no cover
    jax = jnp = None

Params = dict


def _dense_init(key, din, dout):
    k1, _ = jax.random.split(key)
    scale = np.sqrt(2.0 / din)
    return {"w": jax.random.normal(k1, (din, dout)) * scale,
            "b": jnp.zeros((dout,))}


def _dense(p, x, dtype):
    return x @ p["w"].astype(dtype) + p["b"].astype(dtype)


def _ln(p, x):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-6)
    return (out * p["g"] + p["b"]).astype(x.dtype)


def vit_init(key, image_size: int = 224, patch: int = 16, dim: int = 256,
             depth: int = 6, heads: int = 2, mlp_dim: int = 512,
             num_classes: int = 1000) -> Params:
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    n_patches = (image_size // patch) ** 2
    keys = jax.random.split(key, depth * 4 + 3)
    # NOTE: no python scalars in the pytree — the filter layer
    # device-places every leaf, and traced scalars can't drive static
    # shapes (patch derives from embed.w's shape; heads is a call arg)
    params: Params = {
        "embed": {"w": jax.random.normal(
            keys[0], (patch, patch, 3, dim)) * np.sqrt(2.0 / (patch ** 2 * 3)),
            "b": jnp.zeros((dim,))},
        "pos": jax.random.normal(keys[1], (n_patches, dim)) * 0.02,
        "blocks": [],
        "head": _dense_init(keys[2], dim, num_classes),
        "ln_f": {"g": jnp.ones((dim,)), "b": jnp.zeros((dim,))},
    }
    for i in range(depth):
        k = keys[3 + i * 4:3 + (i + 1) * 4]
        params["blocks"].append({
            "ln1": {"g": jnp.ones((dim,)), "b": jnp.zeros((dim,))},
            "qkv": _dense_init(k[0], dim, dim * 3),
            "proj": _dense_init(k[1], dim, dim),
            "ln2": {"g": jnp.ones((dim,)), "b": jnp.zeros((dim,))},
            "mlp1": _dense_init(k[2], dim, mlp_dim),
            "mlp2": _dense_init(k[3], mlp_dim, dim),
        })
    return params


def _attention(block, x, heads: int, dtype):
    from ..ops import flash_attention

    B, S, D = x.shape
    qkv = _dense(block["qkv"], x, dtype)                  # (B,S,3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    dh = D // heads

    def split(t):  # (B,S,D) → (B,H,S,dh)
        return t.reshape(B, S, heads, dh).transpose(0, 2, 1, 3)

    o = flash_attention(split(q), split(k), split(v))
    o = o.transpose(0, 2, 1, 3).reshape(B, S, D)
    return _dense(block["proj"], o, dtype)


def vit_apply(params: Params, x, heads: int = 2, dtype=None):
    """(B, H, W, 3) image → (B, num_classes) logits."""
    if dtype is None:
        dtype = jnp.bfloat16
    patch = params["embed"]["w"].shape[0]
    x = x.astype(dtype)
    x = jax.lax.conv_general_dilated(
        x, params["embed"]["w"].astype(dtype),
        window_strides=(patch, patch), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    B, ph, pw, D = x.shape
    x = x.reshape(B, ph * pw, D) + params["embed"]["b"].astype(dtype)
    x = x + params["pos"].astype(dtype)
    for block in params["blocks"]:
        x = x + _attention(block, _ln(block["ln1"], x), heads, dtype)
        h = _dense(block["mlp1"], _ln(block["ln2"], x), dtype)
        x = x + _dense(block["mlp2"], jax.nn.gelu(h), dtype)
    x = _ln(params["ln_f"], x).mean(axis=1)               # global pool
    return _dense(params["head"], x,
                  jnp.float32).astype(jnp.float32)


def register_vit(name: str = "vit_s16", batch: int = 1,
                 image_size: int = 224, num_classes: int = 1000,
                 heads: int = 2, seed: int = 0, **kw) -> str:
    """Register a ViT in the filter model registry.

    Default ``heads=2`` keeps the head dim at dim/heads = 128 so the
    Pallas flash-attention kernel's tiling check (head dim % 128 == 0,
    ops/kernels.py) passes.  The kernel additionally needs the patch
    sequence length ((image_size/patch)²) to be a multiple of its query
    block (128): 224/16 → 196 patches falls back to the jnp reference;
    use ``image_size=256`` (256 patches) for the full kernel path.
    """
    from ..filters.jax_xla import register_model

    params = vit_init(jax.random.PRNGKey(seed), image_size=image_size,
                      num_classes=num_classes, heads=heads, **kw)
    return register_model(
        name, lambda p, x: vit_apply(p, x, heads=heads), params=params,
        in_shapes=[(batch, image_size, image_size, 3)],
        in_dtypes=np.float32)

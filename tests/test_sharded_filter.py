"""Mesh-sharded ``tensor_filter`` — multi-chip inference from the element
graph.

The reference scales inference out by offloading a tensor_filter to remote
query-server processes over TCP (/root/reference/gst/nnstreamer/
tensor_query/tensor_query_client.c:673-741).  The TPU-native form is the
``mesh=`` / ``sharding=`` filter properties: ONE pjit-compiled invoke spans
a `jax.sharding.Mesh` and XLA inserts the ICI collectives (SURVEY.md §7.6).
These tests run that exact code path over the 8-virtual-CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.elements.filter import FilterSingle, TensorFilter
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.filters import register_model, unregister_model
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.runtime import Pipeline, parse_launch

CPUS = jax.devices("cpu")
pytestmark = pytest.mark.skipif(
    len(CPUS) < 8, reason="needs 8 virtual CPU devices")

RNG = np.random.default_rng(7)
W = RNG.standard_normal((16, 8)).astype(np.float32)
B = RNG.standard_normal((8,)).astype(np.float32)


@pytest.fixture(autouse=True)
def _models():
    register_model("sh_mlp", lambda p, x: jnp.dot(x, p["w"]) + p["b"],
                   params={"w": jnp.asarray(W), "b": jnp.asarray(B)},
                   in_shapes=[(8, 16)])
    register_model("sh_add1", lambda x: x + 1.0, in_shapes=[(8, 16)])
    yield
    unregister_model("sh_mlp")
    unregister_model("sh_add1")


def _expected(x):
    return x.astype(np.float32) @ W + B


class TestFilterSingleMesh:
    def test_data_parallel_invoke(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:-1")
        sp = fs.subplugin
        assert sp._mesh is not None
        assert sp._mesh.devices.size == 8
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)
        # output lives on the whole mesh, not one chip
        assert len(out[0].sharding.device_set) == 8

    def test_tensor_parallel_rules(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:4,model:2",
                          sharding="tp")
        sp = fs.subplugin
        # the dense 'w' (16,8) shards its output dim over model:2
        w = sp._model._mesh_params[(sp._mesh, sp._rules)]["w"]
        spec = w.sharding.spec
        assert tuple(spec) == (None, "model")
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)

    def test_batch1_falls_back_to_replicated_input(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:-1",
                          input_spec=TensorsSpec.parse("16:1", "float32"))
        x = RNG.standard_normal((1, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)

    def test_fixed_axes_use_subset_of_devices(self):
        fs = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", mesh="data:4")
        assert fs.subplugin._mesh.devices.size == 4
        out = fs.invoke([np.zeros((8, 16), np.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)

    def test_bad_mesh_raises(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", mesh="data:3,model:5")
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", mesh="data:-1",
                         sharding="no-such-rules")

    def test_sharding_without_mesh_rejected(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", sharding="tp")

    def test_shared_key_does_not_collide_across_mesh_configs(self):
        plain = FilterSingle(framework="jax-xla", model="sh_add1",
                             accelerator="cpu", shared_key="shk")
        meshed = FilterSingle(framework="jax-xla", model="sh_add1",
                              accelerator="cpu", shared_key="shk",
                              mesh="data:-1")
        assert plain.subplugin._compiled.in_shardings is None
        assert meshed.subplugin._compiled.in_shardings is not None

    def test_set_input_info_keeps_mesh(self):
        fs = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", mesh="data:-1")
        fs.set_input_info(TensorsSpec.parse("4:16", "float32"))
        out = fs.invoke([np.zeros((16, 4), np.float32)])
        assert np.asarray(out[0]).shape == (16, 4)
        assert fs.subplugin._compiled.in_shardings is not None


class TestPipelineMesh:
    def test_parse_launch_mesh_property(self):
        p = parse_launch(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=sh_mlp mesh=data:-1 accelerator=cpu name=f ! "
            "appsink name=out")
        src, f, sink = (p.elements[n] for n in ("src", "f", "out"))
        src.spec = TensorsSpec.parse("16:8", "float32", rate=0)
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        with p:
            src.push_buffer(Buffer.of(x, pts=3))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            out = sink.pull(timeout=1)
            assert f.subplugin._mesh is not None
            assert f.subplugin._mesh.devices.size == 8
        np.testing.assert_allclose(out[0].np(), _expected(x),
                                   rtol=1e-4, atol=1e-4)
        assert out.pts == 3

    def test_fused_prologue_compiles_onto_mesh(self):
        # transform chain fuses into the sharded executable: the whole
        # prologue+model is ONE SPMD program (runtime/fusion.py + mesh=)
        p = Pipeline()
        src = AppSrc(name="src",
                     spec=TensorsSpec.parse("16:8", "uint8", rate=0))
        t = TensorTransform(name="t", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5")
        f = TensorFilter(name="f", framework="jax-xla", model="sh_mlp",
                         accelerator="cpu", mesh="data:-1")
        sink = AppSink(name="out")
        p.add(src, t, f, sink).link(src, t, f, sink)
        x = RNG.integers(0, 255, (8, 16), dtype=np.uint8)
        with p:
            src.push_buffer(Buffer.of(x))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            out = sink.pull(timeout=1)
            c = f.subplugin._compiled
            assert c.with_pre and c.in_shardings is not None
        exp = _expected((x.astype(np.float32) - 127.5) / 127.5)
        np.testing.assert_allclose(out[0].np(), exp, rtol=1e-4, atol=1e-4)

    def test_mesh_matches_single_device_result(self):
        x = RNG.standard_normal((8, 16)).astype(np.float32)

        def run(**fkw):
            p = Pipeline()
            src = AppSrc(name="src",
                         spec=TensorsSpec.parse("16:8", "float32", rate=0))
            f = TensorFilter(name="f", framework="jax-xla", model="sh_mlp",
                             accelerator="cpu", **fkw)
            sink = AppSink(name="out")
            p.add(src, f, sink).link(src, f, sink)
            with p:
                src.push_buffer(Buffer.of(x))
                src.end_of_stream()
                assert p.wait_eos(timeout=60)
                return sink.pull(timeout=1)[0].np()

        np.testing.assert_allclose(
            run(mesh="data:2,model:4", sharding="mobilenet"), run(),
            rtol=1e-4, atol=1e-4)

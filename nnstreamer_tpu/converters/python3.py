"""``python3`` converter sub-plugin: user script → tensors.

Parity target: /root/reference/ext/nnstreamer/tensor_converter/
tensor_converter_python3.cc (414 LoC) and the script contract shown by
tests/test_models/models/custom_converter.py: the script defines a class
``CustomConverter`` whose ``convert(input_arrays)`` receives the raw
input payload(s) as numpy arrays and returns the converted tensors.

Accepted return shapes (most to least structured):
- a :class:`~nnstreamer_tpu.core.Buffer`;
- a list of numpy arrays (specs inferred from dtype/shape);
- the reference 4-tuple ``(tensors_info, raw_data, rate_n, rate_d)``
  where each ``tensors_info[i]`` is ``(dims, np_dtype)`` (nnstreamer
  innermost-first dims) and ``raw_data[i]`` a uint8 payload array.

Reached through ``tensor_converter mode=custom-script:FILE.py``.
"""

from __future__ import annotations

import importlib.util
import os
from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..core import (
    Buffer,
    CapsStruct,
    DType,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    dims_to_shape,
)
from . import ExternalConverter


def _load_script(path: str):
    if not os.path.isfile(path):
        raise FileNotFoundError(f"python3 converter script not found: {path}")
    name = "nns_tpu_conv_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "CustomConverter"):
        raise AttributeError(
            f"{path}: script must define class CustomConverter")
    return mod.CustomConverter()


class Python3Converter(ExternalConverter):
    NAME = "python3"

    def __init__(self, script: str):
        self._obj = _load_script(script)
        self._script = script

    def get_out_config(self, caps: CapsStruct) -> TensorsSpec:
        if hasattr(self._obj, "get_out_config"):
            return self._obj.get_out_config(caps)
        rate = caps.get("framerate", None) if caps is not None else None
        return TensorsSpec(format=TensorFormat.FLEXIBLE,
                           rate=rate or Fraction(0, 1))

    def convert(self, buf: Buffer, caps: CapsStruct) -> Buffer:
        # scripts always see flat uint8 payload views (parity:
        # tensor_converter_python3.cc:150 passes 1-D NPY_UINT8 arrays)
        arrays = [np.frombuffer(t.tobytes(), np.uint8) for t in buf.tensors]
        res = self._obj.convert(arrays)
        out = self._coerce(res)
        out.pts, out.duration = buf.pts, buf.duration
        out.meta.update(buf.meta)
        return out

    @staticmethod
    def _coerce(res) -> Buffer:
        if isinstance(res, Buffer):
            return res
        if isinstance(res, (list, tuple)) and len(res) == 4 \
                and isinstance(res[2], int):
            infos, raw, rate_n, rate_d = res
            tensors: List[Tensor] = []
            for info, payload in zip(infos, raw):
                dims, np_dt = (info if isinstance(info, (tuple, list))
                               else (info.dims, info.dtype))
                dt = DType.from_np(np.dtype(np_dt))
                shape = dims_to_shape(dims)
                arr = np.frombuffer(
                    np.ascontiguousarray(payload).tobytes(),
                    dtype=dt.np_dtype).reshape(shape)
                tensors.append(Tensor(arr, TensorSpec.from_shape(shape, dt)))
            return Buffer(tensors=tensors, format=TensorFormat.FLEXIBLE)
        if isinstance(res, (list, tuple)):
            return Buffer.of(*[np.asarray(a) for a in res])
        raise TypeError(
            "CustomConverter.convert must return Buffer, list of arrays, "
            "or (tensors_info, raw_data, rate_n, rate_d)")

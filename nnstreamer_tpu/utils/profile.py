"""Profiling hooks: XLA/TPU traces with per-element annotation.

Parity target: the reference defers profiling to GStreamer ecosystem
tooling — gst-instruments/gst-top, NNShark (/root/reference/tools/
profiling/README.md) — plus its in-tree per-filter latency/throughput
props.  The TPU-native substitute is the JAX profiler (SURVEY.md §7.7):
``pipeline_trace`` captures a TensorBoard-loadable trace of everything
the pipeline dispatches (XLA kernels, host callbacks, transfers), and
every element's chain runs under a ``TraceAnnotation`` carrying the
element name, so per-element time shows up on the trace timeline the
way gst-top attributes time per GstElement.

Usage::

    from nnstreamer_tpu.utils.profile import pipeline_trace

    with pipeline_trace("/tmp/nns-trace"):
        with pipeline:
            ... stream ...
    # tensorboard --logdir /tmp/nns-trace

Annotations are zero-cost when no trace is active; ``annotate`` is used
by the runtime automatically.
"""

from __future__ import annotations

import contextlib
import threading

_active = threading.Event()


@contextlib.contextmanager
def pipeline_trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a JAX profiler trace of everything run inside."""
    import jax

    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    _active.set()
    try:
        yield log_dir
    finally:
        _active.clear()
        jax.profiler.stop_trace()


def trace_active() -> bool:
    return _active.is_set()


@contextlib.contextmanager
def annotate(name: str):
    """Per-element trace span; no-op unless a trace is being captured."""
    if not _active.is_set():
        yield
        return
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def step_marker(name: str, step: int) -> "contextlib.AbstractContextManager":
    """StepTraceAnnotation for training loops (trainer element epochs)."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def frame_annotation(trace_ids) -> "contextlib.AbstractContextManager":
    """TraceAnnotation naming the obs trace ids riding a dispatch.

    The join key between the two trace worlds: the host-side latency
    tracer (obs/tracer.py, Chrome trace) stamps each sampled frame with
    a process-unique id, and wrapping the XLA dispatch in
    ``nns:frames:<ids>`` makes the same ids searchable on the
    device-side TensorBoard timeline — so a slow frame found in one
    trace can be located in the other.  No-op (and near-free) unless a
    ``pipeline_trace`` capture is active AND the dispatch carries at
    least one sampled frame."""
    if not _active.is_set() or not trace_ids:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(
        "nns:frames:" + ",".join(str(i) for i in trace_ids))

"""Dynamic micro-batching: coalesce in-flight buffers into one dispatch.

The serving-side answer to per-dispatch overhead (Clipper NSDI'17,
TensorFlow Serving's batching layer): whatever requests are in flight
when the window closes are stacked along a leading batch axis and
dispatched as ONE XLA invoke.  The window closes when

- ``max_batch`` buffers are pending (full flush, on the producer thread
  — the producer blocks for the dispatch, which is exactly the
  backpressure that keeps an upstream ``queue`` from being drained
  unboundedly ahead of the device), or
- ``timeout_s`` elapsed since the first buffer entered an empty window
  (deadline flush, on the coalescer's timer thread — bounds the latency
  a lone frame can pay for batching), or
- the element flushes explicitly (EOS/stop: partial batches drain with
  no frame loss).

Bucketed padding keeps the set of compiled shapes small: a partial
window of ``n`` buffers is padded up to the smallest configured bucket
``>= n``, so executables exist only for bucket sizes, not for every
``n`` (XLA compiles per shape; unbounded batch sizes would mean
unbounded recompiles).

Ordering: arrival order is preserved end to end.  Producers append
under the window condition; a flush takes the *serialization lock
first*, then the pending prefix, so two overlapping flushes (full +
deadline) emit downstream in take order even when their device work
completes out of order.

Async dispatch: ``flush_fn`` may ENQUEUE device work and return with
the window's outputs still executing — elements/filter.py pushes jax
arrays downstream as futures and fences only at sinks and sampled-stat
boundaries (Documentation/fusion.md).  Per-stream FIFO survives
unchanged: emission order is fixed by flush-lock acquisition order at
enqueue time, independent of when the device finishes, and an explicit
``flush()`` (EOS/stop) still returns only after every pending window's
``flush_fn`` call issued its work downstream.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..chaos import hooks as _chaos
from ..obs import hooks as _hooks


def parse_buckets(spec: str, max_batch: int) -> Tuple[int, ...]:
    """Resolve the ``batch-buckets`` property into the sorted tuple of
    padded batch sizes.  Empty spec: powers of two up to ``max_batch``.
    ``max_batch`` is always a bucket (a full window never pads); buckets
    above ``max_batch`` are rejected (they could never fill)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if str(spec or "").strip():
        out = set()
        for tok in str(spec).split(","):
            tok = tok.strip()
            if not tok:
                continue
            b = int(tok)
            if b < 1:
                raise ValueError(f"bucket {b} must be >= 1")
            if b > max_batch:
                raise ValueError(
                    f"bucket {b} exceeds batch={max_batch} (a window "
                    f"never holds more than batch buffers)")
            out.add(b)
    else:
        out = set()
        b = 1
        while b < max_batch:
            out.add(b)
            b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n`` frames (buckets sorted
    ascending)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"{n} frames exceed the largest bucket "
                     f"{buckets[-1]}")


class MicroBatcher:
    """Deadline + max-batch request coalescer.

    ``flush_fn(items)`` is invoked with 1..max_batch items, serialized
    (never concurrently) and in arrival order.  Exceptions from a
    producer-triggered (full-window) flush propagate to the producer —
    the element's ``_chain_guarded`` turns them into bus errors;
    exceptions from the timer thread go to ``error_fn``.

    ``adaptive=True`` turns on the idle-flush window (the serving-pool
    policy, runtime/serving.py): when frames are pending and NO flush is
    in flight, the timer dispatches after at most ``settle_s`` instead
    of waiting out the deadline — an idle device never sits out
    ``timeout_s``, while a busy one keeps coalescing until full/deadline
    exactly as before.  The settle interval exists so near-simultaneous
    arrivals from concurrent streams land in ONE window rather than the
    first frame stealing a dispatch all to itself; it bounds the latency
    adaptivity can add to well under the deadline.
    """

    #: adaptive idle-flush settle: how long past a window's first frame
    #: (or the previous flush completing) the timer lets concurrent
    #: arrivals pile in before an idle-device flush (never later than
    #: the deadline).  Too short and N closed-loop streams decay into
    #: stable sub-groups that each steal a dispatch; 1 ms measured best
    #: on the serve bench (both occupancy AND frames/s peak there).
    ADAPTIVE_SETTLE_S = 0.001

    def __init__(self, max_batch: int, timeout_s: float,
                 flush_fn: Callable[[List[Any]], None],
                 error_fn: Optional[Callable[[BaseException], None]] = None,
                 adaptive: bool = False,
                 settle_s: Optional[float] = None,
                 name: str = ""):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.name = name  # trace label (owning element / pool)
        self.max_batch = int(max_batch)
        self.timeout_s = float(timeout_s)
        self.adaptive = bool(adaptive)
        self.settle_s = min(
            self.ADAPTIVE_SETTLE_S if settle_s is None else float(settle_s),
            self.timeout_s)
        self._flush_fn = flush_fn
        self._error_fn = error_fn or (lambda e: None)
        self._pending: List[Any] = []
        self._cv = threading.Condition()
        # taken BEFORE the pending prefix: flush-lock acquisition order
        # IS downstream emission order.  Also the adaptive window's
        # "device busy" signal: held exactly while a flush is in flight.
        # this lock IS the window-flush serialization; holding it
        # across the device invoke is the design (utils/lockdep.py
        # exempts the marked line at the dispatch fence)
        self._flush_serial_lock = threading.Lock()  # nns-lock: dispatch-ok
        self._deadline: Optional[float] = None
        self._last_flush_done = 0.0  # adaptive settle anchor (see below)
        # actuator seam (runtime/actuators.py "coalescing"): while
        # paused, submits park without dispatching — no inline
        # full-window flush, no timer flush.  Explicit flush()/
        # flush_stream() (EOS/stop) IGNORE the pause: frames are never
        # lost to a paused window, only delayed by one.
        self.paused = False
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # introspection (tests / stats): window-close reasons
        self.flushes_full = 0
        self.flushes_deadline = 0
        self.flushes_forced = 0
        self.flushes_adaptive = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        # deterministic name (nns:batch:<owner>) + thread-registry
        # coverage for profiler attribution (obs/prof.py)
        from ..obs import prof as _prof

        self._thread = _prof.named_thread(
            "batch", self.name or "-", self._timer_loop)
        self._thread.start()

    def stop(self) -> None:
        """Stop the timer thread.  Does NOT flush — callers flush first
        (EOS/stop) so pending frames drain in order."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- producer side -------------------------------------------------------

    def submit(self, item: Any) -> None:
        """Enqueue one item; dispatches inline when the window fills."""
        tracer = _hooks.tracer
        if tracer is not None:
            tracer.batch_parked(self, item)
        with self._cv:
            self._pending.append(item)
            full = len(self._pending) >= self.max_batch \
                and not self.paused
            if self._deadline is None:
                self._deadline = time.monotonic() + self.timeout_s
                self._cv.notify_all()
        if full:
            self.flushes_full += 1
            self._drain()

    def flush(self) -> None:
        """Drain every pending item (partial batches included) — the
        EOS/stop path.  Returns once the window is empty and all
        flush_fn calls issued here completed."""
        while True:
            if self._drain() == 0:
                return
            self.flushes_forced += 1

    @property
    def pending(self) -> int:
        with self._cv:
            return len(self._pending)

    # -- actuator seam (runtime/actuators.py) --------------------------------

    def pause(self) -> None:
        """Park-only mode: submits queue, nothing dispatches until
        :meth:`resume` (or an explicit EOS/stop flush, which always
        drains).  The steering use: freeze the window while re-tuning,
        or deliberately compose a cross-stream window in tests."""
        with self._cv:
            self.paused = True

    def resume(self) -> None:
        """Leave park-only mode.  The backlog drains on the TIMER
        thread (the parked window's deadline is long expired, so it
        fires immediately; the adaptive settle paces the rest) and on
        producers' inline full-window flushes — deliberately NOT here:
        resume() is an actuation-plane call, and running dispatch +
        demux inline would let a blocked downstream wedge the caller
        (a controller tick) against the very contract the actuator
        API exists to uphold."""
        with self._cv:
            self.paused = False
            self._cv.notify_all()

    # -- flush machinery -----------------------------------------------------

    def _take_batch_locked(self) -> List[Any]:
        """Select (and remove) the next window's items from the pending
        list — FIFO prefix by default; SharedBatcher overrides this
        with earliest-deadline formation while admission control is
        armed.  Caller holds ``_cv``."""
        batch = self._pending[:self.max_batch]
        del self._pending[:len(batch)]
        return batch

    def _drain(self) -> int:
        """Take up to max_batch pending items (serialized, FIFO) and run
        flush_fn on them.  Returns the number of items flushed."""
        with self._flush_serial_lock:
            with self._cv:
                batch = self._take_batch_locked()
                self._deadline = None if not self._pending \
                    else time.monotonic() + self.timeout_s
            if not batch:
                return 0
            tracer = _hooks.tracer
            if tracer is not None:
                tracer.batch_dispatch(self, batch)
            ch = _chaos.plan
            if ch is not None:
                # queue-pressure seam: an injected dispatch stall backs
                # the window up exactly like a slow device would —
                # producers block on full windows, upstream queues fill
                stall = ch.queue_stall(self.name or "batch")
                if stall > 0:
                    # nns-lint: disable=NNS303 -- intentional: the
                    # injected stall simulates slow device work, which
                    # holds the flush serial lock exactly like a real
                    # dispatch does
                    time.sleep(stall)
            self._flush_fn(batch)
        with self._cv:
            # wake the timer: the dispatch is done, so an adaptive
            # window holding frames that piled up meanwhile can flush
            # now instead of waiting out its deadline
            self._last_flush_done = time.monotonic()
            self._cv.notify_all()
        return len(batch)

    def _timer_loop(self) -> None:
        while True:
            adaptive_fire = False
            with self._cv:
                while self._running:
                    if self._deadline is not None and self._pending \
                            and not self.paused:
                        target = self._deadline
                        idle = self.adaptive and \
                            not self._flush_serial_lock.locked()
                        if idle:
                            # device idle: flush after `settle_s` of
                            # gathering concurrent arrivals.  Anchored
                            # to whichever is later of the window's
                            # first frame (deadline - timeout) and the
                            # last flush completing — results demuxed
                            # at the END of a dispatch trigger the next
                            # round of closed-loop submissions, and
                            # those need the settle window to coalesce
                            # rather than the first one back stealing a
                            # dispatch to itself
                            target = min(target, max(
                                self._deadline - self.timeout_s,
                                self._last_flush_done) + self.settle_s)
                        wait = target - time.monotonic()
                        if wait <= 0:
                            adaptive_fire = idle and \
                                target < self._deadline
                            break
                        self._cv.wait(wait)
                    else:
                        self._cv.wait()
                if not self._running:
                    return
            if adaptive_fire:
                self.flushes_adaptive += 1
            else:
                self.flushes_deadline += 1
            try:
                self._drain()
            except Exception as e:  # noqa: BLE001 - timer thread has no
                # guarded caller; surface via the element's bus
                self._error_fn(e)

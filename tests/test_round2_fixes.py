"""Regression tests for round-2 fixes (VERDICT/ADVICE round 1):
aggregator follow-on window pts, repo EOS-sentinel preservation,
rate closer-frame duplication, declared-property registry, bounding-box
option3 per-scheme interpretation, device-time invoke stats, and the
flexible-stream transform jit cache.
"""

import queue as _q
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make


def drain(sink, timeout=0.2):
    out = []
    while True:
        b = sink.pull(timeout=timeout)
        if b is None:
            return out
        out.append(b)


class TestAggregatorFollowOnPts:
    def test_second_window_from_one_buffer_has_pts(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "8:1", "float32", rate=Fraction(30)))
        # one input buffer carries 4 frames; windows of 2, flush 2 →
        # TWO windows complete per input buffer
        ag = make("tensor_aggregator", el_name="agg", frames_in=4,
                  frames_out=2, frames_flush=2, frames_dim=0)
        sink = AppSink(name="out")
        p.add(src, ag, sink).link(src, ag, sink)
        with p:
            src.push_buffer(Buffer.of(
                np.arange(8, dtype=np.float32).reshape(1, 8), pts=0))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 2
        assert out[0].pts == 0
        # follow-on window: pts0 + flush * frame_duration;
        # frame_duration = 1 / (30 buffers/s × 4 frames/buffer)
        expect = int(2 * 1e9 / (30 * 4))
        assert out[1].pts is not None
        assert abs(out[1].pts - expect) <= 1


class TestRepoEosSentinel:
    def test_displacement_never_drops_eos(self):
        from nnstreamer_tpu.elements.repo import REPO, TensorRepoSink

        REPO.reset()
        snk = TensorRepoSink(name="rs", slot=7)
        d = [Buffer.of(np.full((2,), i, np.float32)) for i in range(3)]
        snk.render(d[0])
        snk.on_eos()           # queue: [d0, EOS]
        snk.render(d[1])       # displaces d0          → [EOS, d1]
        snk.render(d[2])       # displaces EOS — must re-append it
        q = REPO.slot(7)
        items = []
        while True:
            try:
                items.append(q.get_nowait())
            except _q.Empty:
                break
        assert items[-1] is None, "EOS sentinel must stay last"
        assert items.count(None) == 1
        assert any(it is not None for it in items), "newest data kept"


class TestRateCloserFrame:
    def test_slot_gets_nearer_current_frame(self):
        p = Pipeline()
        spec = TensorsSpec.parse("2:1", "float32", rate=Fraction(30))
        src = AppSrc(name="src", spec=spec)
        rate = make("tensor_rate", el_name="r", framerate="10/1")
        sink = AppSink(name="out")
        p.add(src, rate, sink).link(src, rate, sink)
        I = int(1e9 / 10)
        with p:
            src.push_buffer(Buffer.of(
                np.full((1, 2), 0, np.float32), pts=0))
            # arrives just before the 2nd slot: |pts-slot| = 0.1I for the
            # current frame vs 0.9I for the previous one → slot must carry
            # the CURRENT frame, not a one-frame-stale copy
            src.push_buffer(Buffer.of(
                np.full((1, 2), 1, np.float32), pts=int(0.9 * I)))
            src.push_buffer(Buffer.of(
                np.full((1, 2), 2, np.float32), pts=3 * I))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        by_pts = {b.pts: float(b.tensors[0].np()[0, 0]) for b in out}
        assert by_pts[0] == 0.0
        assert by_pts[I] == 1.0  # closer-frame fill (prev would be 0.0)


class TestPropertyRegistry:
    def test_internal_attr_not_settable(self):
        from nnstreamer_tpu.elements.basic import Identity

        el = make("identity", el_name="i")
        with pytest.raises(ValueError, match="no property"):
            el.set_property("stats", {})
        with pytest.raises(ValueError, match="no property"):
            el.set_property("sinkpads", [])

    def test_declared_prop_settable_and_typo_rejected(self):
        el = make("tensor_rate", el_name="r")
        el.set_property("framerate", "5/1")
        assert el.get_property("framerate") == "5/1"
        with pytest.raises(ValueError, match="no property"):
            make("tensor_rate", el_name="r2", framerte="5/1")


class TestBoundingBoxOption3:
    def _dec(self, opts):
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes

        d = BoundingBoxes()
        d.options = [None] * 9
        for i, v in opts.items():
            d.options[i] = v
        d.options_updated()
        return d

    def test_yolo_thresholds(self):
        d = self._dec({0: "yolov5", 2: "0.4:0.6"})
        assert d.conf_thresh == pytest.approx(0.4)
        assert d.iou_thresh == pytest.approx(0.6)

    def test_yolo_with_stale_priors_path_does_not_raise(self):
        # a priors-looking path under a yolo scheme must not hit float()
        d = self._dec({0: "yolov8", 2: "/tmp/0box:priors.txt"})
        assert d.conf_thresh == pytest.approx(0.25)  # defaults kept

    def test_ssd_priors_path_starting_with_digit(self, tmp_path):
        f = tmp_path / "0priors.txt"
        np.savetxt(f, np.ones((4, 4), np.float32))
        d = self._dec({0: "mobilenet-ssd", 2: str(f)})
        assert d.priors is not None and d.priors.shape == (4, 4)


class TestInvokeStatsDeviceTime:
    def test_count_keeps_throughput_without_latency_sample(self):
        from nnstreamer_tpu.utils.stats import InvokeStats

        st = InvokeStats()
        st.record(0.010)
        for _ in range(9):
            st.count()
        assert st.total_invoke_num == 10
        assert st.latency_us == pytest.approx(10_000, rel=0.01)
        assert st.throughput_milli_fps > 0

    def test_filter_samples_block_device(self):
        from nnstreamer_tpu.elements.filter import FilterSingle
        from nnstreamer_tpu.filters.jax_xla import register_model

        register_model("r2_stats_model", lambda x: x * 2,
                       in_shapes=[(2, 2)], in_dtypes=np.float32)
        with FilterSingle(framework="jax-xla",
                          model="r2_stats_model") as f:
            f.invoke([np.ones((2, 2), np.float32)])
            assert f.stats.latency_us >= 0


class TestFlexTransformJitCache:
    def test_same_spec_compiles_once(self):
        from nnstreamer_tpu.core import Tensor, TensorFormat
        from nnstreamer_tpu.elements.transform import TensorTransform

        tr = TensorTransform(name="t", mode="arithmetic",
                             option="add:1.0")
        # no negotiated static caps → flexible path
        for _ in range(3):
            buf = Buffer(tensors=[Tensor(np.zeros((2, 3), np.float32))],
                         format=TensorFormat.FLEXIBLE)
            out = tr.transform(buf)
            np.testing.assert_allclose(out.tensors[0].np(), 1.0)
        assert len(tr._flex_cache) == 1
        buf = Buffer(tensors=[Tensor(np.zeros((4, 3), np.float32))],
                     format=TensorFormat.FLEXIBLE)
        tr.transform(buf)
        assert len(tr._flex_cache) == 2


class TestSsdParamsNotBaked:
    def test_register_end_to_end_passes_params_pytree(self):
        from nnstreamer_tpu.filters.jax_xla import get_model, \
            unregister_model
        from nnstreamer_tpu.models.ssd import register_ssd

        name = register_ssd("r2_ssd_probe", num_classes=5, batch=1,
                            size=64, max_out=4, end_to_end=True)
        try:
            m = get_model(name)
            assert m is not None and m.params is not None
        finally:
            unregister_model(name)

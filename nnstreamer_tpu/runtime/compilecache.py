"""Persistent AOT compilation cache: skip the XLA trace+build across
process restarts.

PR 7's CompileStats made cold-start cost a *measured* number — every
fresh serving process pays the full trace + XLA build for every
(model, schema, bucket) executable it serves, and nothing removed that
cost.  This module does: the ``Lowered``/``Compiled`` objects PR 9's
``_aot_call`` already holds are serialized (``jax.experimental.
serialize_executable``) under ``NNS_TPU_COMPILE_CACHE_DIR``, keyed by
everything that makes two compiles interchangeable::

    (model digest, input schema, bucket, placement canonical key,
     jax version, jaxlib version, backend platform)

A fresh process/host with a warm cache *deserializes* the executable
instead of tracing and building it — measured 10-80x cheaper on the
bench models — and every load is counted into CompileStats under the
new ``persist_hit`` kind, so the cold-start win is an exportable
number (``nns_compiles_total{kind="persist_hit"}``) the
``bench.py --lifecycle`` gate asserts against its own ground truth.

Failure policy: the cache can only ever make things faster, never
wronger or broken.  A corrupt/truncated/version-skewed entry fails the
deserialize and falls back to a normal compile (the bad file is
removed best-effort); an unwritable cache dir disables stores but
leaves serving untouched (and ``nns-lint`` NNS513 warns about the
misconfiguration up front).  Entries carry the jax/jaxlib versions and
backend platform in their *key*, so a version bump or a CPU↔TPU move
simply misses instead of loading an incompatible program.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Callable, Optional

from ..utils.log import logw

#: the one switch: set to a directory to arm the persistent cache
CACHE_ENV = "NNS_TPU_COMPILE_CACHE_DIR"

#: on-disk entry suffix (pickled ``serialize_executable`` 3-tuple)
CACHE_SUFFIX = ".aotx"

_lock = threading.Lock()
#: cache dirs we already warned about (unwritable/missing) — once each
_warned_dirs: set = set()


class CacheStats:
    """Process-wide persistent-cache accounting, pulled like every
    other collected stat (the lifecycle bench asserts
    ``hits == executables loaded``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.errors = 0  # corrupt/unreadable entries, failed stores

    def _bump(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def snapshot(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "stores": self.stores, "errors": self.errors}

    def reset(self) -> None:
        with self._lock:
            self.hits = self.misses = self.stores = self.errors = 0


#: the process-wide persistent-cache stats
CACHE_STATS = CacheStats()


def cache_dir() -> Optional[str]:
    """The armed cache directory, or None when the env is unset.  A
    set-but-missing/unwritable directory returns None too (with one
    warning per directory): a misconfigured cache must degrade to
    "no cache", never to a serving failure."""
    path = os.environ.get(CACHE_ENV, "").strip()
    if not path:
        return None
    if not os.path.isdir(path) or not os.access(path, os.W_OK):
        with _lock:
            if path not in _warned_dirs:
                _warned_dirs.add(path)
                logw("compilecache: %s=%r is not a writable directory "
                     "— persistent AOT cache disabled (nns-lint "
                     "NNS513 flags this)", CACHE_ENV, path)
        return None
    return path


def enabled() -> bool:
    return cache_dir() is not None


def _versions() -> tuple:
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except ImportError:  # pragma: no cover - jaxlib rides with jax
        jl = "?"
    return (getattr(jax, "__version__", "?"), jl)


def _platform() -> str:
    """Backend platform baked into the key: a serialized CPU executable
    must never be offered to a TPU process (it would fail the
    deserialize — but missing outright is cheaper and quieter)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 - key derivation must not raise
        return "?"


def file_digest(path: str) -> str:
    """Content digest of a model file (streamed sha256) — the model
    component of the cache key for file-backed models: editing the
    file in place misses instead of serving stale weights."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def model_digest(model_def: Any) -> str:
    """Digest of a ModelDef-ish object.  File-backed models (``name``
    is an existing file) digest by CONTENT; in-process models digest by
    name + function source (best effort) + the params tree's
    shape/dtype schema.  In-process models are process-local by
    construction (a fresh process re-registers them), so the schema
    digest guards against shape skew — content skew under an unchanged
    name and source is the caller's contract, as documented in
    Documentation/lifecycle.md."""
    name = str(getattr(model_def, "name", "") or "")
    if name and os.path.isfile(name):
        try:
            return "file:" + file_digest(name)
        except OSError:
            pass
    h = hashlib.sha256()
    h.update(name.encode())
    fn = getattr(model_def, "fn", None)
    if fn is not None:
        try:
            import inspect

            h.update(inspect.getsource(fn).encode())
        except (OSError, TypeError):
            h.update(repr(fn).encode())
    params = getattr(model_def, "params", None)
    if params is not None:
        try:
            import jax

            for leaf in jax.tree_util.tree_leaves(params):
                h.update(str(getattr(leaf, "shape", ())).encode())
                h.update(str(getattr(leaf, "dtype", "")).encode())
        except Exception:  # noqa: BLE001 - schema digest is best effort
            pass
    return "obj:" + h.hexdigest()


def make_key(model_dig: str, in_spec: Any, bucket: int,
             placement_key: Any, donate: bool = False) -> str:
    """The persistent key: everything that makes two compiles
    interchangeable, hashed to a filename-safe id."""
    h = hashlib.sha256()
    for part in (model_dig, str(in_spec), str(int(bucket)),
                 repr(placement_key), "donate" if donate else "",
                 *_versions(), _platform()):
        h.update(str(part).encode())
        h.update(b"\x00")
    return h.hexdigest()


def _entry_path(dirpath: str, key: str) -> str:
    return os.path.join(dirpath, key + CACHE_SUFFIX)


def load(key: str) -> Optional[Any]:
    """Deserialize one cached executable; None on miss OR any failure
    (corrupt pickle, truncated payload, version-skewed program — the
    bad entry is removed best-effort and counted as an error)."""
    dirpath = cache_dir()
    if dirpath is None:
        return None
    path = _entry_path(dirpath, key)
    if not os.path.exists(path):
        CACHE_STATS._bump("misses")
        return None
    try:
        from jax.experimental import serialize_executable as _se

        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:  # noqa: BLE001 - ANY load failure means
        # "treat as miss and recompile"; a cache can corrupt in every
        # way a filesystem can, and none of them may break serving
        CACHE_STATS._bump("errors")
        CACHE_STATS._bump("misses")
        logw("compilecache: dropping unreadable entry %s (%s: %s)",
             os.path.basename(path), type(e).__name__, e)
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    CACHE_STATS._bump("hits")
    return compiled


def store(key: str, compiled: Any) -> bool:
    """Serialize one executable under ``key`` (atomic tmp+rename so a
    concurrent reader never sees a torn entry).  False (counted) when
    the backend cannot serialize this program or the write fails."""
    dirpath = cache_dir()
    if dirpath is None:
        return False
    try:
        from jax.experimental import serialize_executable as _se

        blob = pickle.dumps(_se.serialize(compiled))
    except Exception as e:  # noqa: BLE001 - backend-dependent API:
        # an unserializable program just stays uncached
        CACHE_STATS._bump("errors")
        logw("compilecache: cannot serialize executable for %s... "
             "(%s: %s)", key[:12], type(e).__name__, e)
        return False
    path = _entry_path(dirpath, key)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError as e:
        CACHE_STATS._bump("errors")
        logw("compilecache: cannot write %s: %s", path, e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    CACHE_STATS._bump("stores")
    return True


def load_or_compile(key: Optional[str], lowered: Any,
                    stats_kind: str = "persist_hit",
                    bucket: int = 0) -> Any:
    """The one seam ``filters/jax_xla._aot_call`` drives: try the
    persistent cache, fall back to ``lowered.compile()``, store the
    fresh build for the next process.  A cache hit is recorded into
    CompileStats under ``persist_hit`` with the DESERIALIZE time as its
    seconds — the number the cold-start gate compares against the
    trace+build cost it replaced."""
    from ..utils.stats import COMPILE_STATS

    if key is not None:
        t0 = time.perf_counter()
        cached = load(key)
        if cached is not None:
            COMPILE_STATS.record(stats_kind,
                                 time.perf_counter() - t0,
                                 bucket=bucket)
            return cached
    compiled = lowered.compile()
    if key is not None:
        store(key, compiled)
    return compiled

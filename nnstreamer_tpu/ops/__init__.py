"""Pallas TPU kernels for the framework's hot ops.

Where the reference hand-vectorizes with Orc SIMD kernels
(/root/reference/gst/nnstreamer/elements/nnstreamer-orc.orc), this
package holds hand-written TPU kernels for the ops worth owning below
XLA: the streaming normalize/typecast prologue and the flash-attention
block kernel behind long-context attention.  Every kernel has a jnp
reference implementation; callers fall back automatically when shapes
don't tile or Pallas is unavailable.
"""

from .kernels import (
    flash_attention,
    flash_attention_reference,
    scale_bias_cast,
    scale_bias_cast_available,
)

__all__ = [
    "scale_bias_cast", "scale_bias_cast_available",
    "flash_attention", "flash_attention_reference",
]

"""Graph combinator tests: mux/merge/demux/split/join, sync policies,
aggregator, tensor_if, rate, repo loops, sparse enc/dec, crop.

Modeled on the reference suites tests/nnstreamer_mux, tests/nnstreamer_demux,
tests/nnstreamer_if, tests/nnstreamer_rate, tests/nnstreamer_repo_*,
tests/transform_* (SSAT golden pipelines → programmatic equivalents here).
"""

import numpy as np
import pytest
from fractions import Fraction

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.elements.sync import Collector, SyncPolicy
from nnstreamer_tpu.runtime import Pipeline, make, parse_launch


SPEC = TensorsSpec.parse("4", "float32")


def frame(v, pts=None, n=4):
    return Buffer.of(np.full((n,), v, dtype=np.float32), pts=pts)


def two_in_one_out(factory, **props):
    p = Pipeline()
    a = AppSrc(name="a", spec=SPEC)
    b = AppSrc(name="b", spec=SPEC)
    el = make(factory, el_name="x", **props)
    sink = AppSink(name="out")
    p.add(a, b, el, sink)
    p.link_pads(a, "src", el, "sink_0")
    p.link_pads(b, "src", el, "sink_1")
    p.link(el, sink)
    return p, a, b, sink


def drain(sink):
    out = []
    while True:
        buf = sink.pull(timeout=0.2)
        if buf is None:
            return out
        out.append(buf)


class TestMux:
    def test_two_streams_become_two_tensor_frames(self):
        p, a, b, sink = two_in_one_out("tensor_mux")
        with p:
            for i in range(3):
                a.push_buffer(frame(i, pts=i * 100))
                b.push_buffer(frame(10 + i, pts=i * 100))
            a.end_of_stream()
            b.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 3
        assert out[0].num_tensors == 2
        assert out[2].tensors[1].np()[0] == 12.0

    def test_slowest_policy_drops_fast_pad_backlog(self):
        c = Collector(SyncPolicy.parse("slowest"), ["sink_0", "sink_1"])
        # fast pad: pts 0,10,20,30; slow pad arrives at pts 30
        for t in (0, 10, 20, 30):
            assert c.deposit("sink_0", frame(t, pts=t)) == []
        sets = c.deposit("sink_1", frame(99, pts=30))
        assert len(sets) == 1
        assert sets[0]["sink_0"].pts == 30  # older fast buffers dropped
        assert sets[0]["sink_1"].pts == 30

    def test_refresh_policy_reuses_quiet_pad(self):
        c = Collector(SyncPolicy.parse("refresh"), ["sink_0", "sink_1"])
        assert c.deposit("sink_0", frame(1, pts=0)) == []
        s1 = c.deposit("sink_1", frame(2, pts=0))
        assert len(s1) == 1
        # new data only on pad 0: pad 1's last buffer is reused
        s2 = c.deposit("sink_0", frame(3, pts=10))
        assert len(s2) == 1
        assert s2[0]["sink_1"].tensors[0].np()[0] == 2.0

    def test_basepad_policy(self):
        c = Collector(SyncPolicy.parse("basepad", "1:0"),
                      ["sink_0", "sink_1"])
        c.deposit("sink_0", frame(1, pts=0))
        c.deposit("sink_0", frame(2, pts=50))
        sets = c.deposit("sink_1", frame(9, pts=40))
        assert len(sets) == 1
        # base time 40 (pad 1): pad 0 contributes its pts<=40 buffer
        assert sets[0]["sink_0"].pts == 0


class TestMerge:
    def test_concat_innermost_dim(self):
        p, a, b, sink = two_in_one_out("tensor_merge", mode="linear",
                                       option="0")
        with p:
            a.push_buffer(frame(1))
            b.push_buffer(frame(2))
            a.end_of_stream()
            b.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 1
        got = out[0].tensors[0].np()
        np.testing.assert_array_equal(
            got, np.array([1, 1, 1, 1, 2, 2, 2, 2], np.float32))


class TestDemuxSplit:
    def test_demux_tensorpick_reorder(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "4,4,4", "float32,float32,float32"))
        dm = make("tensor_demux", el_name="d", tensorpick="2,0")
        s0, s1 = AppSink(name="o0"), AppSink(name="o1")
        p.add(src, dm, s0, s1)
        p.link(src, dm)
        p.link_pads(dm, "src_0", s0, "sink")
        p.link_pads(dm, "src_1", s1, "sink")
        with p:
            src.push_buffer(Buffer.of(
                *[np.full((4,), i, np.float32) for i in range(3)]))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            b0, b1 = drain(s0), drain(s1)
        assert b0[0].tensors[0].np()[0] == 2.0  # pick 2 first
        assert b1[0].tensors[0].np()[0] == 0.0

    def test_split_by_tensorseg(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("6", "float32"))
        sp = make("tensor_split", el_name="s", tensorseg="2:4", dimension="0")
        s0, s1 = AppSink(name="o0"), AppSink(name="o1")
        p.add(src, sp, s0, s1)
        p.link(src, sp)
        p.link_pads(sp, "src_0", s0, "sink")
        p.link_pads(sp, "src_1", s1, "sink")
        with p:
            src.push_buffer(Buffer.of(
                np.arange(6, dtype=np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            b0, b1 = drain(s0), drain(s1)
        np.testing.assert_array_equal(b0[0].tensors[0].np(), [0, 1])
        np.testing.assert_array_equal(b1[0].tensors[0].np(), [2, 3, 4, 5])

    def test_join_first_come_forward(self):
        p, a, b, sink = two_in_one_out("join")
        with p:
            a.push_buffer(frame(1))
            b.push_buffer(frame(2))
            a.push_buffer(frame(3))
            a.end_of_stream()
            b.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        # arrival order across the two source threads is not deterministic;
        # join must forward every buffer exactly once
        assert sorted(int(o.tensors[0].np()[0]) for o in out) == [1, 2, 3]


class TestAggregator:
    def test_batch_4_frames(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "8:1", "float32", rate=Fraction(30)))
        ag = make("tensor_aggregator", el_name="agg", frames_in=1,
                  frames_out=4, frames_dim=0)
        sink = AppSink(name="out")
        p.add(src, ag, sink).link(src, ag, sink)
        with p:
            for i in range(8):
                src.push_buffer(Buffer.of(
                    np.full((1, 8), i, np.float32), pts=i))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 2
        assert out[0].tensors[0].shape == (1, 32)
        assert out[1].tensors[0].np()[0, 8] == 5.0

    def test_sliding_window_flush(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("2:1", "float32"))
        ag = make("tensor_aggregator", el_name="agg", frames_in=1,
                  frames_out=2, frames_flush=1, frames_dim=0)
        sink = AppSink(name="out")
        p.add(src, ag, sink).link(src, ag, sink)
        with p:
            for i in range(3):
                src.push_buffer(Buffer.of(np.full((1, 2), i, np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        # windows: [0,1], [1,2] (overlap via flush=1)
        assert len(out) == 2
        np.testing.assert_array_equal(
            out[1].tensors[0].np(), [[1, 1, 2, 2]])


class TestIf:
    def _run_if(self, frames, **props):
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        tif = make("tensor_if", el_name="i", **props)
        then_s, else_s = AppSink(name="t"), AppSink(name="e")
        p.add(src, tif, then_s, else_s)
        p.link(src, tif)
        p.link_pads(tif, "src_then", then_s, "sink")
        p.link_pads(tif, "src_else", else_s, "sink")
        with p:
            for f in frames:
                src.push_buffer(f)
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            return drain(then_s), drain(else_s)

    def test_average_threshold_routes_branches(self):
        t, e = self._run_if(
            [frame(1), frame(5), frame(2)],
            compared_value="TENSOR_AVERAGE_VALUE",
            compared_value_option="0", operator="ge", supplied_value="3",
            then="PASSTHROUGH", else_="PASSTHROUGH")
        assert [int(b.tensors[0].np()[0]) for b in t] == [5]
        assert [int(b.tensors[0].np()[0]) for b in e] == [1, 2]

    def test_else_fill_zero(self):
        t, e = self._run_if(
            [frame(5), frame(1)],
            compared_value="A_VALUE", compared_value_option="0:0",
            operator="gt", supplied_value="3",
            then="PASSTHROUGH", else_="FILL_ZERO")
        assert len(t) == 1 and len(e) == 1
        np.testing.assert_array_equal(e[0].tensors[0].np(), np.zeros(4))

    def test_custom_callback(self):
        from nnstreamer_tpu.elements.condition import (
            register_if_callback,
            unregister_if_callback,
        )

        register_if_callback("odd", lambda b: int(b.tensors[0].np()[0]) % 2)
        try:
            t, e = self._run_if(
                [frame(1), frame(2), frame(3)],
                compared_value="CUSTOM", compared_value_option="odd",
                then="PASSTHROUGH", else_="PASSTHROUGH")
            assert [int(b.tensors[0].np()[0]) for b in t] == [1, 3]
            assert [int(b.tensors[0].np()[0]) for b in e] == [2]
        finally:
            unregister_if_callback("odd")

    def test_range_operator_and_repeat_prev(self):
        t, e = self._run_if(
            [frame(5), frame(50), frame(7)],
            compared_value="A_VALUE", compared_value_option="0:0",
            operator="range_inclusive", supplied_value="0:10",
            then="PASSTHROUGH", else_="REPEAT_PREVIOUS_FRAME")
        assert [int(b.tensors[0].np()[0]) for b in t] == [5, 7]
        # else branch repeated nothing (no prior else frame) → empty
        assert e == []


class TestRate:
    def test_downsample_drops(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "4", "float32", rate=Fraction(10)))
        rt = make("tensor_rate", el_name="r", framerate="5/1")
        sink = AppSink(name="out")
        p.add(src, rt, sink).link(src, rt, sink)
        SEC = 1_000_000_000
        with p:
            for i in range(10):  # 10 fps for 1s
                src.push_buffer(frame(i, pts=i * SEC // 10))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 5  # halved
        assert rt.drop_count == 5

    def test_upsample_duplicates(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "4", "float32", rate=Fraction(5)))
        rt = make("tensor_rate", el_name="r", framerate="10/1")
        sink = AppSink(name="out")
        p.add(src, rt, sink).link(src, rt, sink)
        SEC = 1_000_000_000
        with p:
            for i in range(5):
                src.push_buffer(frame(i, pts=i * SEC // 5))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 9  # last slot has no following frame
        assert rt.dup_count == 4


class TestRepoLoop:
    def test_accumulator_feedback(self):
        """reposrc → transform(add 1) → tee → reposink + sink: a counter
        loop (parity: tests/nnstreamer_repo_dynamicity)."""
        from nnstreamer_tpu.elements.repo import REPO

        REPO.reset()
        p = parse_launch(
            "tensor_reposrc name=loop slot=0 num_buffers=5 "
            "caps=other/tensors,format=static,num_tensors=1,"
            "dimensions=1,types=float32,framerate=0/1 ! "
            "tensor_transform mode=arithmetic option=add:1 ! "
            "tee name=t ! tensor_reposink slot=0 t. ! appsink name=out")
        sink = p["out"]
        with p:
            # generous timeout: the transform's first jit can queue behind
            # other tests' device work on a shared/tunneled chip
            assert p.wait_eos(timeout=90)
            out = drain(sink)
        vals = [float(b.tensors[0].np().ravel()[0]) for b in out]
        assert vals == [1.0, 2.0, 3.0, 4.0, 5.0]


class TestSparse:
    def test_roundtrip_through_pipeline(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("8", "float32"))
        enc = make("tensor_sparse_enc", el_name="enc")
        dec = make("tensor_sparse_dec", el_name="dec")
        sink = AppSink(name="out")
        p.add(src, enc, dec, sink).link(src, enc, dec, sink)
        x = np.array([0, 0, 3, 0, 0, 0, 7, 0], np.float32)
        with p:
            src.push_buffer(Buffer.of(x))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        np.testing.assert_array_equal(out[0].tensors[0].np(), x)

    def test_sparse_payload_smaller_for_sparse_data(self):
        from nnstreamer_tpu.core.buffer import sparse_from_dense
        from nnstreamer_tpu.core import Tensor

        dense = np.zeros((1000,), np.float32)
        dense[3] = 1.0
        assert len(sparse_from_dense(Tensor(dense))) < dense.nbytes // 4


class TestCrop:
    def test_crop_regions(self):
        p = Pipeline()
        raw = AppSrc(name="raw", spec=TensorsSpec.parse("3:8:8", "uint8"))
        info = AppSrc(name="info", spec=TensorsSpec.parse("4:2", "uint32"))
        crop = make("tensor_crop", el_name="c")
        sink = AppSink(name="out")
        p.add(raw, info, crop, sink)
        p.link_pads(raw, "src", crop, "sink_raw")
        p.link_pads(info, "src", crop, "sink_info")
        p.link(crop, sink)
        img = np.arange(8 * 8 * 3, dtype=np.uint8).reshape(8, 8, 3)
        regions = np.array([[1, 2, 4, 3], [0, 0, 2, 2]], np.uint32)
        with p:
            raw.push_buffer(Buffer.of(img))
            info.push_buffer(Buffer.of(regions))
            raw.end_of_stream()
            info.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 1 and out[0].num_tensors == 2
        np.testing.assert_array_equal(
            out[0].tensors[0].np(), img[2:5, 1:5, :])
        np.testing.assert_array_equal(
            out[0].tensors[1].np(), img[0:2, 0:2, :])


class TestCapsScalarDims:
    def test_scalar_dimensions_caps_string_intersects(self):
        """Regression: dimensions=1 in a caps string must stay a string so
        the dimensions special-case in intersection applies."""
        from nnstreamer_tpu.core import Caps
        from nnstreamer_tpu.runtime.parser import parse_caps_string

        a = parse_caps_string(
            "other/tensors,format=static,num_tensors=1,dimensions=1,"
            "types=uint8,framerate=0/1")
        b = Caps.from_spec(TensorsSpec.parse("1", "uint8"))
        assert a.can_intersect(b)
        assert a.fixate().to_spec().tensors[0].dims == (1,)


class TestAggregatorBacklog:
    def test_fin_gt_fout_emits_all_windows(self):
        """Regression: frames_in > frames_out must emit every window, not
        one per input buffer."""
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("4:1", "float32"))
        ag = make("tensor_aggregator", el_name="agg", frames_in=4,
                  frames_out=2, frames_dim=0)
        sink = AppSink(name="out")
        p.add(src, ag, sink).link(src, ag, sink)
        with p:
            for i in range(2):  # 8 frames total
                src.push_buffer(Buffer.of(
                    np.arange(4 * i, 4 * i + 4, dtype=np.float32
                              ).repeat(1).reshape(1, 4)))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        assert len(out) == 4  # 8 frames / 2 per window
        np.testing.assert_array_equal(out[3].tensors[0].np(), [[6, 7]])

    def test_concat_false_caps_match_payload(self):
        """Regression: concat=False must negotiate fout per-frame tensors."""
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("4:1", "float32"))
        ag = make("tensor_aggregator", el_name="agg", frames_in=1,
                  frames_out=2, frames_dim=0, concat=False)
        sink = AppSink(name="out")
        p.add(src, ag, sink).link(src, ag, sink)
        with p:
            for i in range(2):
                src.push_buffer(Buffer.of(np.full((1, 4), i, np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
            spec = ag.srcpad.spec  # read before stop clears pad caps
        assert spec.num_tensors == 2
        assert out[0].num_tensors == 2
        assert out[0].tensors[0].shape == (1, 4)


class TestRatePrevFrameSemantics:
    def test_gap_slots_carry_previous_frame(self):
        """Regression: upsampling duplicates the PREVIOUS frame into gap
        slots — content never appears earlier than its own pts."""
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse(
            "4", "float32", rate=Fraction(5)))
        rt = make("tensor_rate", el_name="r", framerate="10/1")
        sink = AppSink(name="out")
        p.add(src, rt, sink).link(src, rt, sink)
        SEC = 1_000_000_000
        with p:
            src.push_buffer(frame(0, pts=0))
            src.push_buffer(frame(1, pts=SEC // 5))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = drain(sink)
        # slots: 0 (frame0), 0.1s (dup of frame0), 0.2s (frame1)
        vals = [(b.pts, int(b.tensors[0].np()[0])) for b in out]
        assert vals == [(0, 0), (SEC // 10, 0), (SEC // 5, 1)]

"""Minimal .tflite model importer: flatbuffer reader + graph → JAX.

Parity target: the reference's flagship tensorflow-lite filter
sub-plugin (/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_tensorflow_lite.cc:242-280 loads a .tflite file and
invokes it through the TFLite interpreter).  TPU-native redesign:
instead of linking an interpreter, the graph is IMPORTED — a
hand-rolled flatbuffer walk (no flatc codegen, same policy as the
wire codecs in converters/codecs.py) extracts tensors, quantization
params and the operator list, and the whole network is rebuilt as ONE
jittable JAX function that XLA compiles for the accelerator.
Quantized (uint8/int8) graphs run LOW-PRECISION by default (round-4
verdict #1), with the mode picked by measurement on v5e:

- ``qmode="bf16"`` (the quantized-graph default): weights and
  activations bf16-resident — half the f32 HBM bytes at zero
  conversion cost on the MXU's native dtype.  Measured (fetch-synced
  chained dispatch, batch 256, v5e): 6.0 ms/batch = 42.6k fps/chip vs
  12.0 ms = 21.4k float on the reference quant mobilenet_v2 — 2.0x —
  with the "orange" golden intact.
- ``qmode="dequant"``: true quantized execution — weights AND
  inter-op activations stay uint8 on device (1/4 the bytes; XLA cost
  analysis confirms 1.9 vs 5.6 GB/batch), operands lift to
  integer-valued bf16 with f32 accumulation and the requantize
  epilogue fuses into each conv (_build_fn_quant).  Measured 8.8
  ms/batch (29.0k fps): beats float but loses to bf16 — the
  u8<->bf16 conversion chains eat most of what the narrower bytes
  save.  Kept as the exact-integer-arithmetic mode.
- ``qmode="float"``: dequantize-at-load f32 (round-4 semantics).

Supported op set covers the reference's test models (mobilenet_v1/v2
classifiers and friends): CONV_2D, DEPTHWISE_CONV_2D, ADD, PAD,
AVERAGE_POOL_2D, MAX_POOL_2D, FULLY_CONNECTED, RESHAPE, SQUEEZE,
SOFTMAX, MEAN, RELU, RELU6, LOGISTIC, CONCATENATION.  Anything else
raises with the op name so the gap is explicit.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .importer_util import batch_flex_target

# -- flatbuffer primitives ---------------------------------------------------


class _FB:
    """Just enough of the flatbuffers binary format to walk a .tflite:
    root offset, vtable-indexed field lookup, vectors, strings."""

    def __init__(self, buf: bytes):
        self.b = buf

    def u8(self, p):
        return self.b[p]

    def u16(self, p):
        return struct.unpack_from("<H", self.b, p)[0]

    def i32(self, p):
        return struct.unpack_from("<i", self.b, p)[0]

    def u32(self, p):
        return struct.unpack_from("<I", self.b, p)[0]

    def i64(self, p):
        return struct.unpack_from("<q", self.b, p)[0]

    def f32(self, p):
        return struct.unpack_from("<f", self.b, p)[0]

    def root(self) -> int:
        return self.u32(0)

    def field(self, table: int, fid: int) -> Optional[int]:
        """Absolute position of field ``fid``'s inline data, or None if
        absent (vtable default)."""
        vt = table - self.i32(table)
        if 4 + 2 * fid >= self.u16(vt):
            return None
        off = self.u16(vt + 4 + 2 * fid)
        return table + off if off else None

    def indirect(self, p: int) -> int:
        return p + self.u32(p)

    def table_field(self, table: int, fid: int) -> Optional[int]:
        p = self.field(table, fid)
        return None if p is None else self.indirect(p)

    def vec(self, table: int, fid: int) -> Optional[Tuple[int, int]]:
        """(start, length) of a vector field; elements follow ``start``."""
        p = self.field(table, fid)
        if p is None:
            return None
        v = self.indirect(p)
        return v + 4, self.u32(v)

    def vec_i32(self, table: int, fid: int) -> Optional[np.ndarray]:
        se = self.vec(table, fid)
        if se is None:
            return None
        s, n = se
        return np.frombuffer(self.b, "<i4", count=n, offset=s).copy()

    def vec_f32(self, table: int, fid: int) -> Optional[np.ndarray]:
        se = self.vec(table, fid)
        if se is None:
            return None
        s, n = se
        return np.frombuffer(self.b, "<f4", count=n, offset=s).copy()

    def vec_i64(self, table: int, fid: int) -> Optional[np.ndarray]:
        se = self.vec(table, fid)
        if se is None:
            return None
        s, n = se
        return np.frombuffer(self.b, "<i8", count=n, offset=s).copy()

    def vec_bytes(self, table: int, fid: int) -> Optional[bytes]:
        se = self.vec(table, fid)
        if se is None:
            return None
        s, n = se
        return self.b[s:s + n]

    def vec_tables(self, table: int, fid: int) -> List[int]:
        se = self.vec(table, fid)
        if se is None:
            return []
        s, n = se
        return [self.indirect(s + 4 * i) for i in range(n)]

    def string(self, table: int, fid: int) -> str:
        p = self.field(table, fid)
        if p is None:
            return ""
        v = self.indirect(p)
        n = self.u32(v)
        return self.b[v + 4:v + 4 + n].decode("utf-8", "replace")

    def scalar(self, table: int, fid: int, kind: str, default=0):
        p = self.field(table, fid)
        if p is None:
            return default
        return getattr(self, kind)(p)


# -- tflite schema field ids (schema.fbs) ------------------------------------

# TensorType
_TT_FLOAT32, _TT_FLOAT16, _TT_INT32 = 0, 1, 2
_TT_UINT8, _TT_INT64, _TT_INT8 = 3, 4, 9
_TT_NP = {_TT_FLOAT32: np.float32, _TT_FLOAT16: np.float16,
          _TT_INT32: np.int32, _TT_UINT8: np.uint8, _TT_INT64: np.int64,
          _TT_INT8: np.int8}

# BuiltinOperator (deprecated_builtin_code values; 3.x models use these)
_OPS = {0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
        4: "DEPTHWISE_CONV_2D", 9: "FULLY_CONNECTED", 14: "LOGISTIC",
        17: "MAX_POOL_2D", 18: "MUL", 19: "RELU", 21: "RELU6",
        22: "RESHAPE", 23: "RESIZE_BILINEAR", 25: "SOFTMAX", 34: "PAD",
        40: "MEAN", 43: "SQUEEZE"}

_ACT = {0: None, 1: "relu", 3: "relu6"}


def _act(code: int):
    """Map a fused_activation_function code; raise on unsupported codes
    (RELU_N1_TO_1=2, TANH=4, SIGN_BIT=5) so the gap is explicit rather
    than a silently dropped activation."""
    if code not in _ACT:
        raise NotImplementedError(
            f"tflite: unsupported fused_activation_function code {code}")
    return _ACT[code]


class TFLiteTensor:
    __slots__ = ("shape", "ttype", "buffer", "name", "scale", "zero",
                 "qdim")

    def __init__(self, shape, ttype, buffer, name, scale, zero, qdim=0):
        self.shape, self.ttype, self.buffer = shape, ttype, buffer
        self.name, self.scale, self.zero = name, scale, zero
        self.qdim = qdim


class TFLiteModel:
    """Parsed model: tensor table, constant buffers, operator list."""

    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray)):
            buf = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                buf = f.read()
        fb = _FB(buf)
        model = fb.indirect(0)
        # Model: version=0 operator_codes=1 subgraphs=2 desc=3 buffers=4
        self.opcodes = []
        for oc in fb.vec_tables(model, 1):
            # OperatorCode: deprecated_builtin_code=0 (int8),
            # custom_code=1, version=2, builtin_code=3 (int32)
            code = fb.scalar(oc, 3, "i32", 0) or fb.scalar(oc, 0, "u8", 0)
            self.opcodes.append(_OPS.get(code, f"op#{code}"))
        self.buffers = []
        for b in fb.vec_tables(model, 4):
            self.buffers.append(fb.vec_bytes(b, 0))
        subgraphs = fb.vec_tables(model, 2)
        if not subgraphs:
            raise ValueError("tflite: no subgraphs")
        sg = subgraphs[0]
        # SubGraph: tensors=0 inputs=1 outputs=2 operators=3 name=4
        self.tensors: List[TFLiteTensor] = []
        for t in fb.vec_tables(sg, 0):
            # Tensor: shape=0 type=1 buffer=2 name=3 quantization=4
            q = fb.table_field(t, 4)
            scale = zero = None
            qdim = 0
            if q is not None:
                # QuantizationParameters: min=0 max=1 scale=2
                # zero_point=3 details_type=4 details=5
                # quantized_dimension=6
                sc = fb.vec_f32(q, 2)
                zp = fb.vec_i64(q, 3)
                if sc is not None and sc.size:
                    scale = sc
                    zero = zp if zp is not None and zp.size else \
                        np.zeros_like(sc, np.int64)
                    qdim = fb.scalar(q, 6, "i32", 0)
            self.tensors.append(TFLiteTensor(
                fb.vec_i32(t, 0), fb.scalar(t, 1, "u8", 0),
                fb.scalar(t, 2, "u32", 0), fb.string(t, 3), scale, zero,
                qdim))
        def _ids(vec):
            return [] if vec is None else [int(v) for v in vec]

        self.inputs = _ids(fb.vec_i32(sg, 1))
        self.outputs = _ids(fb.vec_i32(sg, 2))
        self.operators = []
        for op in fb.vec_tables(sg, 3):
            # Operator: opcode_index=0 inputs=1 outputs=2
            #           builtin_options_type=3 builtin_options=4
            self.operators.append({
                "op": self.opcodes[fb.scalar(op, 0, "u32", 0)],
                "inputs": _ids(fb.vec_i32(op, 1)),
                "outputs": _ids(fb.vec_i32(op, 2)),
                "options": fb.table_field(op, 4),
            })
        self._fb = fb

    # -- constants -----------------------------------------------------------

    def const(self, idx: int, dequant: bool = True) -> Optional[np.ndarray]:
        """Materialize tensor ``idx``'s constant data (dequantized to
        float32 when it carries quantization params), or None if it is
        an activation (empty buffer)."""
        t = self.tensors[idx]
        raw = self.buffers[t.buffer] if t.buffer < len(self.buffers) else None
        if not raw:
            return None
        arr = np.frombuffer(raw, _TT_NP[t.ttype]).reshape(
            t.shape if t.shape is not None and len(t.shape) else -1)
        if dequant and t.scale is not None and \
                t.ttype in (_TT_UINT8, _TT_INT8):
            scale, zero = t.scale, t.zero
            if scale.size > 1:  # per-channel along quantized_dimension
                shape = [1] * arr.ndim
                shape[t.qdim] = scale.size
                scale = scale.reshape(shape)
                zero = zero.reshape(shape)
            arr = (arr.astype(np.float32) - zero.astype(np.float32)) * \
                scale.astype(np.float32)
        elif dequant and t.scale is not None and t.ttype == _TT_INT32:
            # bias: int32 with scale = input_scale * weight_scale
            scale = t.scale
            if scale.size > 1:
                scale = scale.reshape([-1])
            arr = arr.astype(np.float32) * scale.astype(np.float32)
        return arr


# -- graph → jax --------------------------------------------------------------


def _resize_bilinear(x, oh, ow, align_corners: bool, half_pixel: bool):
    """NHWC bilinear resize matching TFLite's three sampling grids
    (half-pixel centers / align-corners / legacy floor)."""
    import jax.numpy as jnp

    def axis_coords(n_in, n_out):
        i = jnp.arange(n_out, dtype=jnp.float32)
        if align_corners and n_out > 1:
            src = i * (n_in - 1) / (n_out - 1)
        elif half_pixel:
            src = (i + 0.5) * n_in / n_out - 0.5
        else:
            src = i * n_in / n_out
        src = jnp.clip(src, 0.0, n_in - 1)
        lo = jnp.floor(src).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n_in - 1)
        w = src - lo.astype(jnp.float32)
        return lo, hi, w

    h_lo, h_hi, h_w = axis_coords(x.shape[1], oh)
    w_lo, w_hi, w_w = axis_coords(x.shape[2], ow)
    top = jnp.take(x, h_lo, axis=1)
    bot = jnp.take(x, h_hi, axis=1)
    rows = top + (bot - top) * h_w[None, :, None, None]
    left = jnp.take(rows, w_lo, axis=2)
    right = jnp.take(rows, w_hi, axis=2)
    return left + (right - left) * w_w[None, None, :, None]


def _same_pad(in_size, stride, k, dilation: int = 1):
    k_eff = (k - 1) * dilation + 1
    out = -(-in_size // stride)
    pad = max((out - 1) * stride + k_eff - in_size, 0)
    return pad // 2, pad - pad // 2


#: ops whose SECOND input is structural (shapes/axes/sizes consumed at
#: trace time, not tensor data)
_STRUCTURAL_OPS = {"RESHAPE", "PAD", "MEAN", "RESIZE_BILINEAR"}


def build_fn(model: TFLiteModel, qmode: str = "auto"):
    """Compile the op list into ``fn(params, x) -> output`` (single
    input/output graphs — the reference's filter contract for its test
    models).  Weights travel in ``params`` (a {tensor_index: array}
    pytree the filter layer device-places) rather than baked into the
    HLO as literals — the same rule the zoo follows
    (models/ssd.py ssd_detect_apply); structural constants (reshape
    shapes, pad widths, reduce axes) stay concrete.  Output is
    float32.  Returns (fn, params, in_shape, in_dtype).

    ``qmode`` (round-4 verdict #1 — quantization as an EXECUTION mode):

    - "auto": "dequant" when the graph is quantized, else "float";
    - "dequant": weights AND inter-op activations stay uint8 on device
      (4x fewer HBM bytes); conv/matmul operands are lifted u8 → bf16
      integer values (exact) on the MXU with f32 accumulation, scales
      fold into the fused requantize epilogue (_build_fn_quant);
    - "float": dequantize everything at load, run f32 with the
      output-range saturation clamps (round-4 semantics).
    """
    import jax
    import jax.numpy as jnp

    fbm = model
    if qmode not in ("auto", "bf16", "dequant", "float"):
        raise ValueError(f"tflite: unknown qmode {qmode!r}")
    quantized = fbm.tensors[fbm.inputs[0]].scale is not None and \
        fbm.tensors[fbm.inputs[0]].ttype in (_TT_UINT8, _TT_INT8)
    if qmode == "auto":
        # bf16 measured 2.0x float and 1.5x uint8-resident execution
        # on v5e (module doc): half the bytes at zero conversion cost
        # on the MXU's native dtype is the sweet spot
        qmode = "bf16" if quantized else "float"
    if qmode == "dequant":
        if not quantized:
            raise ValueError(
                "tflite: qmode dequant needs a quantized graph")
        return _build_fn_quant(fbm)
    in_idx = fbm.inputs[0]
    out_idx = fbm.outputs[0]
    consts: Dict[int, Any] = {}
    for i in range(len(fbm.tensors)):
        c = fbm.const(i)
        if c is not None:
            consts[i] = c
    fb = fbm._fb
    structural = set()
    for op in fbm.operators:
        if op["op"] in _STRUCTURAL_OPS and len(op["inputs"]) > 1:
            structural.add(op["inputs"][1])
    weights = {str(i): arr for i, arr in consts.items()
               if i not in structural}
    cdt = jnp.bfloat16 if qmode == "bf16" else jnp.float32
    if qmode == "bf16":
        # bf16-RESIDENT weights and activations: half the HBM bytes of
        # f32 at zero conversion cost (MXU-native dtype); the output
        # returns f32 (filter contract)
        weights = {k: np.asarray(v, dtype=jnp.bfloat16.dtype)
                   if getattr(v, "dtype", None) == np.float32 else v
                   for k, v in weights.items()}
        consts = {i: (np.asarray(v, dtype=jnp.bfloat16.dtype)
                      if i not in structural and
                      getattr(v, "dtype", None) == np.float32 else v)
                  for i, v in consts.items()}

    def opt(op, fid, kind, default=0):
        return default if op["options"] is None else \
            fb.scalar(op["options"], fid, kind, default)

    def fn(params, x):
        t = fbm.tensors[in_idx]
        x = x.astype(cdt)
        if t.scale is not None:
            x = (x - jnp.asarray(float(t.zero[0]), cdt)) * \
                jnp.asarray(float(t.scale[0]), cdt)
        vals: Dict[int, Any] = {in_idx: x}

        def get(i):
            if i in vals:
                return vals[i]
            key = str(i)
            if key in params:
                return jnp.asarray(params[key])
            return jnp.asarray(consts[i])

        for op in fbm.operators:
            name = op["op"]
            ins, outs = op["inputs"], op["outputs"]
            if name == "CONV_2D":
                xi, w = get(ins[0]), get(ins[1])
                b = get(ins[2]) if len(ins) > 2 and ins[2] >= 0 else None
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                pad = opt(op, 0, "u8", 0)  # 0=SAME 1=VALID
                # Conv2DOptions: dilation_w_factor=4 dilation_h_factor=5
                dw_, dh = opt(op, 4, "u32", 1) or 1, \
                    opt(op, 5, "u32", 1) or 1
                padding = [_same_pad(xi.shape[1], sh, w.shape[1], dh),
                           _same_pad(xi.shape[2], sw, w.shape[2], dw_)] \
                    if pad == 0 else [(0, 0), (0, 0)]
                y = jax.lax.conv_general_dilated(
                    xi, w, (sh, sw), padding,
                    rhs_dilation=(dh, dw_),
                    dimension_numbers=("NHWC", "OHWI", "NHWC"))
                if b is not None:
                    y = y + b
                act = _act(opt(op, 3, "u8", 0))
            elif name == "DEPTHWISE_CONV_2D":
                xi, w = get(ins[0]), get(ins[1])
                b = get(ins[2]) if len(ins) > 2 and ins[2] >= 0 else None
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                pad = opt(op, 0, "u8", 0)
                # DepthwiseConv2DOptions: dilation_w=5 dilation_h=6
                ddw, ddh = opt(op, 5, "u32", 1) or 1, \
                    opt(op, 6, "u32", 1) or 1
                c = xi.shape[-1]
                # tflite dw weights: (1, kh, kw, c*mult) → HWIO (kh,kw,1,c)
                wk = w.reshape(w.shape[1], w.shape[2], 1, -1)
                padding = [_same_pad(xi.shape[1], sh, w.shape[1], ddh),
                           _same_pad(xi.shape[2], sw, w.shape[2], ddw)] \
                    if pad == 0 else [(0, 0), (0, 0)]
                y = jax.lax.conv_general_dilated(
                    xi, wk, (sh, sw), padding,
                    rhs_dilation=(ddh, ddw),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=c)
                if b is not None:
                    y = y + b
                act = _act(opt(op, 4, "u8", 0))
            elif name == "ADD":
                y = get(ins[0]) + get(ins[1])
                act = _act(opt(op, 0, "u8", 0))
            elif name == "MUL":
                y = get(ins[0]) * get(ins[1])
                act = _act(opt(op, 0, "u8", 0))
            elif name == "PAD":
                pads = consts[ins[1]]
                y = jnp.pad(get(ins[0]),
                            [tuple(p) for p in np.asarray(pads)])
                act = None
            elif name in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
                xi = get(ins[0])
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                kw, kh = opt(op, 3, "u32", 1), opt(op, 4, "u32", 1)
                padmode = "SAME" if opt(op, 0, "u8", 0) == 0 else "VALID"
                if name == "MAX_POOL_2D":
                    y = jax.lax.reduce_window(
                        xi, -jnp.inf, jax.lax.max,
                        (1, kh, kw, 1), (1, sh, sw, 1), padmode)
                else:
                    # average over the actual window population (SAME
                    # pads contribute neither sum nor count — TF
                    # semantics)
                    y = jax.lax.reduce_window(
                        xi, 0.0, jax.lax.add,
                        (1, kh, kw, 1), (1, sh, sw, 1), padmode)
                    ones = jnp.ones(xi.shape[:3] + (1,), xi.dtype)
                    cnt = jax.lax.reduce_window(
                        ones, 0.0, jax.lax.add,
                        (1, kh, kw, 1), (1, sh, sw, 1), padmode)
                    y = y / cnt
                act = _act(opt(op, 5, "u8", 0))
            elif name == "MEAN":
                axes = tuple(int(a) for a in np.asarray(consts[ins[1]]))
                keep = bool(opt(op, 0, "u8", 0))
                y = jnp.mean(get(ins[0]), axis=axes, keepdims=keep)
                act = None
            elif name == "FULLY_CONNECTED":
                xi, w = get(ins[0]), get(ins[1])
                y = xi.reshape(xi.shape[0], -1) @ w.T
                if len(ins) > 2 and ins[2] >= 0 and ins[2] in consts:
                    y = y + get(ins[2])
                act = _act(opt(op, 0, "u8", 0))
            elif name == "RESHAPE":
                shape = consts.get(ins[1]) if len(ins) > 1 else None
                if shape is None:
                    shape = fbm.tensors[outs[0]].shape
                v = get(ins[0])
                tgt = batch_flex_target(
                    tuple(int(s) for s in shape), v.shape,
                    int(x.shape[0]) if getattr(x, "ndim", 0) else 1,
                    recorded_src=fbm.tensors[ins[0]].shape)
                y = v.reshape(tgt)
                act = None
            elif name == "SQUEEZE":
                # SqueezeOptions: squeeze_dims=0 (list); absent → all
                # size-1 dims EXCEPT the batch axis (keep the schema
                # batch-flexible, same contract as RESHAPE)
                dims = [] if op["options"] is None else [
                    int(d) for d in _opt_ints(fb, op["options"], 0)]
                xi = get(ins[0])
                if not dims:
                    dims = [d for d in range(1, xi.ndim)
                            if xi.shape[d] == 1]
                y = jnp.squeeze(xi, axis=tuple(dims))
                act = None
            elif name == "RESIZE_BILINEAR":
                xi = get(ins[0])
                oh, ow = (int(v) for v in np.asarray(consts[ins[1]]))
                # ResizeBilinearOptions: align_corners=2
                # half_pixel_centers=3; the three TF sampling grids
                align = bool(opt(op, 2, "u8", 0))
                half = bool(opt(op, 3, "u8", 0))
                y = _resize_bilinear(xi, oh, ow, align, half)
                act = None
            elif name == "SOFTMAX":
                beta = opt(op, 0, "f32", 1.0) or 1.0
                y = jax.nn.softmax(get(ins[0]) * beta, axis=-1)
                act = None
            elif name == "LOGISTIC":
                y = jax.nn.sigmoid(get(ins[0]))
                act = None
            elif name == "RELU":
                y = jnp.maximum(get(ins[0]), 0.0)
                act = None
            elif name == "RELU6":
                y = jnp.clip(get(ins[0]), 0.0, 6.0)
                act = None
            elif name == "CONCATENATION":
                axis = opt(op, 0, "i32", 0)
                y = jnp.concatenate([get(i) for i in ins], axis=axis)
                act = _act(opt(op, 1, "u8", 0))
            else:
                raise NotImplementedError(
                    f"tflite: unsupported op {name} "
                    f"(inputs {[fbm.tensors[i].name for i in ins]})")
            if act == "relu":
                y = jnp.maximum(y, 0.0)
            elif act == "relu6":
                y = jnp.clip(y, 0.0, 6.0)
            # Quantized graphs encode activations in the OUTPUT tensor's
            # representable range (fused_activation_function stays NONE;
            # e.g. a Relu6 output has zero_point 0, scale 6/255): clamp
            # each activation to its quantized range, reproducing both
            # the nonlinearity and uint8 saturation in float.
            to = fbm.tensors[outs[0]]
            if to.scale is not None and to.ttype in (_TT_UINT8, _TT_INT8):
                qmin, qmax = (0, 255) if to.ttype == _TT_UINT8 \
                    else (-128, 127)
                sc, zp = float(to.scale[0]), float(to.zero[0])
                y = jnp.clip(y, (qmin - zp) * sc, (qmax - zp) * sc)
            vals[outs[0]] = y
        return vals[out_idx].astype(jnp.float32)

    in_t = fbm.tensors[in_idx]
    in_shape = tuple(int(s) for s in in_t.shape)
    in_dtype = _TT_NP[in_t.ttype]
    return fn, weights, in_shape, in_dtype


def _opt_ints(fb, options, fid):
    """Read a flatbuffer int-vector option field (e.g. squeeze_dims)."""
    vec = fb.vec_i32(options, fid)
    return [] if vec is None else list(vec)


def _build_fn_quant(fbm: TFLiteModel):
    """Quantized execution: activations travel uint8/int8 between ops,
    weights stay in their stored integer dtype, and each conv/matmul
    lifts its operands to integer-valued bf16 (exact: the quantized
    range fits bf16's mantissa) for the MXU, accumulating f32.  The
    requantize epilogue — one f32 multiply (``s_x*s_w/s_y``), round,
    clip, narrow — fuses into the conv.  HBM traffic is 1/4 of the
    float path for both weights and activations, which is what the
    roofline says this bandwidth-bound model needs.

    Padding note: PAD and SAME-padding pad the LIFTED (zero-point-
    subtracted) operand, so zero-valued padding is exact.
    """
    import jax
    import jax.numpy as jnp

    in_idx = fbm.inputs[0]
    out_idx = fbm.outputs[0]
    consts_raw: Dict[int, Any] = {}
    for i in range(len(fbm.tensors)):
        c = fbm.const(i, dequant=False)
        if c is not None:
            consts_raw[i] = c
    fb = fbm._fb
    structural = set()
    for op in fbm.operators:
        if op["op"] in _STRUCTURAL_OPS and len(op["inputs"]) > 1:
            structural.add(op["inputs"][1])
    weights = {str(i): arr for i, arr in consts_raw.items()
               if i not in structural}

    def opt(op, fid, kind, default=0):
        return default if op["options"] is None else \
            fb.scalar(op["options"], fid, kind, default)

    def qp(i):
        t = fbm.tensors[i]
        if t.scale is None:
            return None
        return (t.scale.astype(np.float32), t.zero.astype(np.float32),
                t.qdim, t.ttype)

    def fn(params, x):
        def get(i):
            if i in vals:
                return vals[i]
            key = str(i)
            if key in params:
                return jnp.asarray(params[key])
            return jnp.asarray(consts_raw[i])

        def lift(i, ndim_for_qdim=None):
            """tensor i → integer-valued bf16 (zero-point removed)."""
            v = get(i)
            q = qp(i)
            if q is None:
                return v.astype(jnp.bfloat16)
            s, z, qdim, _tt = q
            if z.size > 1 and ndim_for_qdim is not None:
                shape = [1] * ndim_for_qdim
                shape[qdim] = z.size
                z = z.reshape(shape)
            else:
                z = float(z[0])
            return v.astype(jnp.bfloat16) - jnp.asarray(z, jnp.bfloat16)

        def deq(i, v=None):
            """tensor i → real-valued f32."""
            v = get(i) if v is None else v
            q = qp(i)
            if q is None:
                return v.astype(jnp.float32)
            s, z, qdim, _tt = q
            if s.size > 1:
                shape = [1] * v.ndim
                shape[qdim] = s.size
                s = s.reshape(shape)
                z = z.reshape(shape)
            else:
                s, z = float(s[0]), float(z[0])
            return (v.astype(jnp.float32) - z) * s

        def req(i, real, act=None):
            """real-valued f32 → tensor i's quantized storage."""
            if act == "relu":
                real = jnp.maximum(real, 0.0)
            elif act == "relu6":
                real = jnp.clip(real, 0.0, 6.0)
            q = qp(i)
            if q is None:
                return real
            s, z, _qdim, tt = q
            lo, hi = (0, 255) if tt == _TT_UINT8 else (-128, 127)
            y = jnp.round(real / float(s[0])) + float(z[0])
            return jnp.clip(y, lo, hi).astype(
                jnp.uint8 if tt == _TT_UINT8 else jnp.int8)

        def wscale(i):
            """weight scale vector (per-channel or scalar) as f32."""
            s, _z, _qdim, _tt = qp(i)
            return s

        # input: accept the declared quantized dtype directly, or
        # requantize a float input (e.g. an upstream transform)
        t_in = fbm.tensors[in_idx]
        if x.dtype == _TT_NP[t_in.ttype]:
            vals: Dict[int, Any] = {in_idx: x}
        else:
            vals = {in_idx: None}
            vals[in_idx] = req(in_idx, x.astype(jnp.float32))

        for op in fbm.operators:
            name = op["op"]
            ins, outs = op["inputs"], op["outputs"]
            o = outs[0]
            if name in ("CONV_2D", "DEPTHWISE_CONV_2D"):
                dw = name == "DEPTHWISE_CONV_2D"
                xi = lift(ins[0])
                w_raw = get(ins[1])
                w = lift(ins[1], ndim_for_qdim=4)
                act = _act(opt(op, 4 if dw else 3, "u8", 0))
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                pad = opt(op, 0, "u8", 0)
                if dw:
                    d_w = opt(op, 5, "u32", 1) or 1
                    d_h = opt(op, 6, "u32", 1) or 1
                    c = xi.shape[-1]
                    w = w.reshape(w.shape[1], w.shape[2], 1, -1)
                    dn = ("NHWC", "HWIO", "NHWC")
                    groups = c
                    kh, kw = w_raw.shape[1], w_raw.shape[2]
                else:
                    d_w = opt(op, 4, "u32", 1) or 1
                    d_h = opt(op, 5, "u32", 1) or 1
                    dn = ("NHWC", "OHWI", "NHWC")
                    groups = 1
                    kh, kw = w_raw.shape[1], w_raw.shape[2]
                padding = [_same_pad(xi.shape[1], sh, kh, d_h),
                           _same_pad(xi.shape[2], sw, kw, d_w)] \
                    if pad == 0 else [(0, 0), (0, 0)]
                acc = jax.lax.conv_general_dilated(
                    xi, w, (sh, sw), padding,
                    rhs_dilation=(d_h, d_w),
                    dimension_numbers=dn,
                    feature_group_count=groups,
                    preferred_element_type=jnp.float32)
                # bias: int32 at scale s_x*s_w — same units as acc
                if len(ins) > 2 and ins[2] >= 0:
                    acc = acc + get(ins[2]).astype(jnp.float32)
                s_x = float(qp(ins[0])[0][0])
                m = (s_x * wscale(ins[1])).reshape(1, 1, 1, -1)
                vals[o] = req(o, acc * m, act)
            elif name == "FULLY_CONNECTED":
                xi = lift(ins[0])
                xi = xi.reshape(xi.shape[0], -1)
                w = lift(ins[1], ndim_for_qdim=2)
                act = _act(opt(op, 0, "u8", 0))
                acc = jax.lax.dot_general(
                    xi, w, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                if len(ins) > 2 and ins[2] >= 0 and ins[2] in consts_raw:
                    acc = acc + get(ins[2]).astype(jnp.float32)
                s_x = float(qp(ins[0])[0][0])
                m = (s_x * wscale(ins[1])).reshape(1, -1)
                vals[o] = req(o, acc * m, act)
            elif name in ("ADD", "MUL"):
                act = _act(opt(op, 0, "u8", 0))
                a, b = deq(ins[0]), deq(ins[1])
                vals[o] = req(o, a + b if name == "ADD" else a * b, act)
            elif name == "PAD":
                # quantized pad: fill with the zero-point (real 0)
                pads = [tuple(p) for p in
                        np.asarray(consts_raw[ins[1]])]
                q = qp(ins[0])
                fill = 0 if q is None else int(q[1][0])
                vals[o] = jnp.pad(get(ins[0]), pads,
                                  constant_values=fill)
            elif name == "MAX_POOL_2D":
                # max is monotone in q-space: pool the u8/i8 directly
                # (init = dtype min so negative int8 windows and SAME
                # padding cannot inject spurious zeros)
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                kw_, kh_ = opt(op, 3, "u32", 1), opt(op, 4, "u32", 1)
                padmode = "SAME" if opt(op, 0, "u8", 0) == 0 else "VALID"
                act = _act(opt(op, 5, "u8", 0))
                xi = get(ins[0])
                pooled = jax.lax.reduce_window(
                    xi, jnp.array(np.iinfo(np.dtype(xi.dtype)).min,
                                  xi.dtype), jax.lax.max,
                    (1, kh_, kw_, 1), (1, sh, sw, 1), padmode)
                if act is not None:
                    # rare: fused act on a quantized maxpool — apply in
                    # real space against the INPUT qparams (maxpool
                    # preserves them), requantize to the output
                    vals[o] = req(o, deq(ins[0], pooled), act)
                else:
                    vals[o] = pooled
            elif name == "AVERAGE_POOL_2D":
                sh, sw = opt(op, 2, "u32", 1), opt(op, 1, "u32", 1)
                kw_, kh_ = opt(op, 3, "u32", 1), opt(op, 4, "u32", 1)
                padmode = "SAME" if opt(op, 0, "u8", 0) == 0 else "VALID"
                act = _act(opt(op, 5, "u8", 0))
                xi = deq(ins[0])
                ssum = jax.lax.reduce_window(
                    xi, 0.0, jax.lax.add,
                    (1, kh_, kw_, 1), (1, sh, sw, 1), padmode)
                ones = jnp.ones(xi.shape[:3] + (1,), xi.dtype)
                cnt = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add,
                    (1, kh_, kw_, 1), (1, sh, sw, 1), padmode)
                vals[o] = req(o, ssum / cnt, act)
            elif name == "MEAN":
                axes = tuple(int(a) for a in
                             np.asarray(consts_raw[ins[1]]))
                keep = bool(opt(op, 0, "u8", 0))
                vals[o] = req(o, jnp.mean(deq(ins[0]), axis=axes,
                                          keepdims=keep))
            elif name in ("RESHAPE", "SQUEEZE"):
                v = get(ins[0])
                if name == "SQUEEZE":
                    dims = [] if op["options"] is None else [
                        int(d) for d in _opt_ints(fb, op["options"], 0)]
                    if not dims:
                        dims = [d for d in range(1, v.ndim)
                                if v.shape[d] == 1]
                    vals[o] = jnp.squeeze(v, axis=tuple(dims))
                else:
                    shape = consts_raw.get(ins[1]) if len(ins) > 1 \
                        else None
                    if shape is None:
                        shape = fbm.tensors[outs[0]].shape
                    tgt = batch_flex_target(
                        tuple(int(t) for t in shape), v.shape,
                        int(x.shape[0]) if getattr(x, "ndim", 0) else 1,
                        recorded_src=fbm.tensors[ins[0]].shape)
                    vals[o] = v.reshape(tgt)
            elif name == "CONCATENATION":
                axis = opt(op, 0, "i32", 0)
                act = _act(opt(op, 1, "u8", 0))
                vals[o] = req(o, jnp.concatenate(
                    [deq(i) for i in ins], axis=axis), act)
            elif name == "SOFTMAX":
                beta = opt(op, 0, "f32", 1.0) or 1.0
                vals[o] = req(o, jax.nn.softmax(
                    deq(ins[0]) * beta, axis=-1))
            elif name == "LOGISTIC":
                vals[o] = req(o, jax.nn.sigmoid(deq(ins[0])))
            elif name == "RELU":
                vals[o] = req(o, jnp.maximum(deq(ins[0]), 0.0))
            elif name == "RELU6":
                vals[o] = req(o, jnp.clip(deq(ins[0]), 0.0, 6.0))
            elif name == "RESIZE_BILINEAR":
                oh, ow = (int(v) for v in
                          np.asarray(consts_raw[ins[1]]))
                align = bool(opt(op, 2, "u8", 0))
                half = bool(opt(op, 3, "u8", 0))
                vals[o] = req(o, _resize_bilinear(
                    deq(ins[0]), oh, ow, align, half))
            else:
                raise NotImplementedError(
                    f"tflite: unsupported op {name} in quantized "
                    f"execution "
                    f"(inputs {[fbm.tensors[i].name for i in ins]})")
        return deq(out_idx, vals[out_idx])

    in_t = fbm.tensors[in_idx]
    in_shape = tuple(int(s) for s in in_t.shape)
    in_dtype = _TT_NP[in_t.ttype]
    return fn, weights, in_shape, in_dtype

"""Decoder sub-plugins (L3): tensor streams → media/semantic streams.

Parity target: the decoder sub-plugin ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_decoder.h:38-99):
``init/exit``, ``setOption``, ``getOutCaps``, ``decode``, registered under a
mode string; sub-plugin inventory per
/root/reference/ext/nnstreamer/tensor_decoder/ (SURVEY.md §2.4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Type

from ..core import Buffer, Caps, TensorsSpec

_lock = threading.Lock()
_decoders: Dict[str, Type["Decoder"]] = {}


class Decoder:
    """One decode mode (e.g. image_labeling, bounding_boxes)."""

    MODE = ""

    def __init__(self):
        self.options: List[str] = [""] * 9

    def set_option(self, index: int, value: str) -> None:
        """Parity: option1..option9 properties of tensor_decoder."""
        while len(self.options) <= index:
            self.options.append("")
        self.options[index] = value
        self.options_updated()

    def options_updated(self) -> None:
        pass

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        raise NotImplementedError

    def wants_host_input(self) -> bool:
        """Whether decode() reads the input tensors on host.  True for
        every reference decoder (they are CPU rasterizers); a decoder
        that renders on-device returns False so tensor_decoder skips the
        device→host prefetch entirely."""
        return True

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        raise NotImplementedError


def register_decoder(cls: Type[Decoder]) -> Type[Decoder]:
    if not cls.MODE:
        raise ValueError(f"{cls.__name__} has empty MODE")
    with _lock:
        _decoders[cls.MODE] = cls
    return cls


def find_decoder(mode: str) -> Type[Decoder]:
    _ensure_builtin()
    with _lock:
        try:
            return _decoders[mode]
        except KeyError:
            known = ", ".join(sorted(_decoders))
            raise KeyError(
                f"no decoder mode {mode!r}; known: {known}") from None


def list_decoders():
    _ensure_builtin()
    with _lock:
        return sorted(_decoders)


_builtin_done = False
_builtin_lock = threading.Lock()


def _ensure_builtin() -> None:
    global _builtin_done
    if _builtin_done:
        return
    with _builtin_lock:
        if _builtin_done:
            return
        from . import directvideo, imagelabel  # noqa: F401
        for mod in ("boundingbox", "imagesegment", "pose", "tensorregion",
                    "octetstream", "flexbuf", "wirefmt", "python3"):
            __import__(f"{__name__}.{mod}")
        _builtin_done = True

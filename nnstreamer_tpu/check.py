"""``python -m nnstreamer_tpu.check`` — installation self-check.

Parity target: the reference's ``nnstreamer-check`` utility (meson
``enable-nnstreamer-check``): lists registered elements, filter
frameworks, decoder/converter sub-plugins, and the visible accelerator
inventory, so a deployment can verify what this installation provides.
"""

from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    as_json = "--json" in argv

    from .converters import list_converters
    from .decoders import list_decoders
    from .filters.registry import list_filters
    from .runtime.registry import list_elements
    from .utils.hw import probe

    info = {
        "elements": list_elements(),
        "filter_frameworks": list_filters(),
        "decoders": list_decoders(),
        "converters": list_converters(),
        "devices": probe(),
    }
    try:
        from .nativelib import get_native

        info["native_codec"] = get_native() is not None
    except Exception:  # noqa: BLE001
        info["native_codec"] = False
    if as_json:
        print(json.dumps(info, indent=2, default=str))
        return 0
    print("nnstreamer-tpu installation check")
    print(f"- elements ({len(info['elements'])}): "
          + ", ".join(info["elements"]))
    print(f"- filter frameworks: {', '.join(info['filter_frameworks'])}")
    print(f"- decoders: {', '.join(info['decoders'])}")
    print(f"- converters: {', '.join(info['converters'])}")
    print(f"- native codec: {'yes' if info['native_codec'] else 'no'}")
    for platform, devs in info["devices"].items():
        kinds = {d["kind"] for d in devs}
        print(f"- {platform}: {len(devs)} device(s) ({', '.join(kinds)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

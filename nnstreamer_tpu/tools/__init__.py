"""Developer tools (console scripts — see pyproject ``[project.scripts]``).

Parity: /root/reference/tools/development/ (code generator, pipeline
parser) — here shipped inside the installable package.
"""

"""Trainer sub-plugin layer: in-pipeline training backends.

Parity target: the trainer sub-plugin ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_trainer.h:60-117
— ``create/destroy/start/stop/push_data/getStatus`` plus an event
notifier the sub-plugin uses to signal ``EPOCH_COMPLETION`` /
``TRAINING_COMPLETION``), consumed by the tensor_trainer element
(gst/nnstreamer/elements/gsttensor_trainer.c).

The flagship backend is :mod:`.jax_optax` — where the reference delegates
to nntrainer on one device, this trains with a jitted, mesh-sharded
optax step (parallel/sharded.py train_step): forward, backward, gradient
all-reduce over ICI, and the optimizer update are ONE XLA computation.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Type

# event types the sub-plugin sends through its notifier
# (parity: GstTensorTrainerEventType)
EVENT_EPOCH_COMPLETION = "epoch-completion"
EVENT_TRAINING_COMPLETION = "training-completion"


@dataclasses.dataclass
class TrainerProps:
    """Read-only trainer configuration (parity:
    GstTensorTrainerProperties)."""

    framework: str = ""
    model_config: Any = None      # dict, or path to a JSON config
    model_save_path: str = ""
    model_load_path: str = ""
    num_inputs: int = 1
    num_labels: int = 1
    num_training_samples: int = 0
    num_validation_samples: int = 0
    num_epochs: int = 1


class TrainerError(Exception):
    pass


class TrainerSubplugin:
    """Base class every trainer backend implements.

    ``error`` and ``finished`` are part of the ABI: the element polls
    ``error`` to surface failures instead of blocking a full epoch
    timeout, and waits on ``finished`` to gate EOS on training
    completion."""

    NAME: str = ""

    def __init__(self):
        self.props: Optional[TrainerProps] = None
        self.notify: Optional[Callable[[str, Dict], None]] = None
        self.error: Optional[BaseException] = None
        self.finished = threading.Event()

    def configure(self, props: TrainerProps,
                  notify: Callable[[str, Dict], None]) -> None:
        """create(): resolve the model/optimizer from props."""
        self.props = props
        self.notify = notify

    def start(self) -> None:
        """Begin accepting samples (training may run asynchronously)."""

    def push_data(self, inputs: List, labels: List,
                  is_validation: bool = False) -> None:
        """Feed ONE sample (already split into inputs/labels)."""
        raise NotImplementedError

    def get_status(self) -> Dict[str, float]:
        """Current ``epoch``, ``training_loss``, ``training_accuracy``,
        ``validation_loss``, ``validation_accuracy``."""
        raise NotImplementedError

    def save(self, path: str) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        """destroy(): stop training and release resources."""


_lock = threading.Lock()
_trainers: Dict[str, Type[TrainerSubplugin]] = {}


def register_trainer(cls: Type[TrainerSubplugin]) -> Type[TrainerSubplugin]:
    with _lock:
        _trainers[cls.NAME] = cls
    return cls


def find_trainer(name: str) -> Type[TrainerSubplugin]:
    with _lock:
        try:
            return _trainers[name]
        except KeyError:
            known = ", ".join(sorted(_trainers))
            raise KeyError(
                f"no trainer sub-plugin {name!r}; known: {known}") from None


from . import jax_optax  # noqa: E402,F401  (registers the flagship)

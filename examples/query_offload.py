#!/usr/bin/env python
"""Among-device AI: a client pipeline offloads its filter stage to a
server pipeline over localhost TCP (tensor_query elements).

    python examples/query_offload.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.runtime import Pipeline, make
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc

    spec = TensorsSpec.parse("4:1", "float32")
    register_custom_easy("double", lambda xs: [xs[0] * 2.0],
                         in_spec=spec, out_spec=spec)

    server = Pipeline(name="server")
    qsrc = make("tensor_query_serversrc", el_name="qsrc",
                connect_type="tcp", host="127.0.0.1", port=0, id=1)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="double")
    qsink = make("tensor_query_serversink", el_name="qsink", id=1)
    server.add(qsrc, flt, qsink).link(qsrc, flt, qsink)

    with server:
        port = qsrc.port
        print(f"server pipeline listening on 127.0.0.1:{port}")
        client = Pipeline(name="client")
        src = AppSrc(name="src", spec=spec)
        cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
                   port=port, connect_type="tcp", timeout=30000)
        out = AppSink(name="out")
        client.add(src, cli, out).link(src, cli, out)
        with client:
            for i in range(3):
                src.push_buffer(Buffer.of(
                    np.full((1, 4), float(i + 1), np.float32)))
            src.end_of_stream()
            client.wait_eos(timeout=30)
            while True:
                b = out.pull(timeout=0.5)
                if b is None:
                    break
                print("offloaded result:", b.tensors[0].np().ravel())


if __name__ == "__main__":
    main()

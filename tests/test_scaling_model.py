"""Scaling-projection model tests (round-4 verdict #8): pin the
arithmetic of bench.scaling_projection so the v5e-8 claim rests on a
checked model, not a pro-rating."""

import pytest

from nnstreamer_tpu.bench import V5E_ICI_BYTES_PER_S, scaling_projection


class TestScalingProjection:
    def test_data_parallel_is_linear_minus_margin(self):
        p = scaling_projection(15000.0, 2e9, 1000.0, n_chips=8,
                               host_fanout_margin=0.03)
        assert p["data_parallel"]["projected_fps"] == pytest.approx(
            15000 * 8 * 0.97, rel=1e-6)
        assert p["data_parallel"]["ici_traffic"] == 0

    def test_split_pipeline_ici_not_binding_for_tiny_handoff(self):
        # the shipped split hands off decoded detections (~KB/frame):
        # demand orders of magnitude below supply → efficiency 1.0
        p = scaling_projection(15000.0, 2e9, 1000.0, n_chips=8)
        assert p["split_pipeline"]["ici_efficiency"] == 1.0
        assert p["split_pipeline"]["ici_demand_bytes_per_s"] < \
            p["split_pipeline"]["ici_supply_bytes_per_s"]

    def test_split_pipeline_ici_binds_for_huge_handoff(self):
        # a hypothetical raw-feature-map handoff big enough to saturate
        # the boundary: efficiency = supply/demand < 1 and the
        # projected fps scales down by exactly that factor
        huge = 1e9  # 1 GB/frame
        p = scaling_projection(15000.0, 2e9, huge, n_chips=8)
        eff = p["split_pipeline"]["ici_efficiency"]
        assert eff < 1.0
        ideal = 15000.0 * 4 * 0.97
        # when ICI binds, throughput collapses to supply/handoff
        assert p["split_pipeline"]["projected_fps"] == pytest.approx(
            4 * V5E_ICI_BYTES_PER_S / huge, rel=1e-6)
        assert eff == pytest.approx(
            (4 * V5E_ICI_BYTES_PER_S) / (ideal * huge), abs=5e-4)

    def test_split_pipeline_paced_by_full_program_stage(self):
        # the shipped split's stage A runs the full per-chip program on
        # half the chips: steady-state is HALF the data-parallel number
        # (a compute-balanced split would approach dp; this one exists
        # for placement, not throughput)
        p = scaling_projection(15000.0, 2e9, 1000.0, n_chips=8)
        assert p["split_pipeline"]["projected_fps"] == pytest.approx(
            15000 * 4 * 0.97, rel=1e-6)

    def test_projection_is_labeled_a_model(self):
        p = scaling_projection(1000.0, 1e9, 0.0)
        assert "NOT a measurement" in p["model"]
        assert p["inputs"]["fps_per_chip_measured"] == 1000.0

"""``flexbuf`` decoder: tensors → self-describing flexible wire payloads.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-flexbuf.cc (235 LoC): serializes each tensor with its schema so
the receiving side (converter sub-plugin ``flexbuf``,
tensor_converter_flexbuf.cc) can reconstruct it without out-of-band caps —
the framework's native wire format (core/meta.py header || payload).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import (
    Buffer,
    Caps,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
)
from . import Decoder, register_decoder


@register_decoder
class FlexBuf(Decoder):
    MODE = "flexbuf"

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.from_spec(TensorsSpec(
            format=TensorFormat.FLEXIBLE, rate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        payloads = buf.pack_flexible()
        tensors = [
            Tensor(np.frombuffer(p, np.uint8),
                   TensorSpec.from_shape((len(p),), np.uint8))
            for p in payloads]
        return Buffer(tensors=tensors, pts=buf.pts, duration=buf.duration,
                      format=TensorFormat.FLEXIBLE, meta=dict(buf.meta))

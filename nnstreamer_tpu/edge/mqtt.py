"""MQTT elements: ``mqttsrc`` / ``mqttsink`` + a loopback broker.

Parity targets:
- /root/reference/gst/mqtt/mqttsink.c (1418 LoC) / mqttsrc.c (1423 LoC):
  publish/subscribe whole tensor buffers over MQTT topics; props host
  (127.0.0.1), port (1883), client-id, pub-topic/sub-topic, num-buffers,
  mqtt-qos (0 = fire-and-forget, the default), keep-alive.
- mqttcommon.h:49-63 ``GstMQTTMessageHdr``: the publisher prepends
  {num_mems, per-memory sizes, base/sent epoch (for latency estimation;
  NTP-disciplined in the reference, pluggable clock here), duration,
  dts, pts, caps string} to the payload — same layout idea, fixed-width
  little-endian fields (struct format ``_HDR_FMT`` below).

The MQTT 3.1.1 client (CONNECT/CONNACK, PUBLISH QoS0, SUBSCRIBE/SUBACK,
PING, DISCONNECT) is implemented directly over TCP — no paho dependency
— and :class:`MiniBroker` is an in-process broker for loopback pipelines
and tests (the reference likewise tests against a mocked broker,
tests/gstreamer_mqtt/unittest_mqtt_w_helper.cc).
"""

from __future__ import annotations

import os
import queue as _q
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..chaos.retrypolicy import RetryPolicy
from ..core import Buffer, Caps, Tensor, TensorFormat, TensorSpec, TensorsSpec
from ..obs import hooks as _hooks
from ..obs import tracectx
from ..obs.metrics import LinkMetrics
from ..obs.tracer import TRACE_META_KEY
from ..runtime.element import SinkElement, SourceElement, StreamError
from ..runtime.registry import register_element

# -- MQTT 3.1.1 packet codec -------------------------------------------------

_CONNECT, _CONNACK = 1, 2
_PUBLISH = 3
_SUBSCRIBE, _SUBACK = 8, 9
_PINGREQ, _PINGRESP = 12, 13
_DISCONNECT = 14


def _enc_varlen(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("mqtt: peer closed")
        data += chunk
    return data


def _read_packet(sock: socket.socket,
                 first_byte: Optional[int] = None) -> Tuple[int, int, bytes]:
    """→ (type, flags, payload)."""
    h = _read_exact(sock, 1)[0] if first_byte is None else first_byte
    length = shift = 0
    while True:
        b = _read_exact(sock, 1)[0]
        length |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 21:
            raise StreamError("mqtt: bad remaining-length")
    return h >> 4, h & 0x0F, _read_exact(sock, length) if length else b""


def _packet(ptype: int, flags: int, payload: bytes) -> bytes:
    return bytes([ptype << 4 | flags]) + _enc_varlen(len(payload)) + payload


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


class MqttClient:
    """Tiny MQTT 3.1.1 client: QoS0 publish/subscribe over TCP."""

    def __init__(self, host: str, port: int, client_id: str,
                 keep_alive: int = 60, timeout: float = 10.0):
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.settimeout(timeout)
        self._wlock = threading.Lock()
        self._closed = threading.Event()
        # PUBLISHes a spec-compliant broker may interleave before a
        # SUBACK (MQTT 3.1.1 §3.8.4) — parked here for recv_publish
        self._pending: List[Tuple[str, bytes]] = []
        var = _mqtt_str("MQTT") + bytes([4])  # protocol level 3.1.1
        var += bytes([0x02])                  # clean session
        var += struct.pack(">H", keep_alive)
        var += _mqtt_str(client_id)
        self._sock.sendall(_packet(_CONNECT, 0, var))
        t, _, p = _read_packet(self._sock)
        if t != _CONNACK or len(p) < 2 or p[1] != 0:
            raise StreamError(f"mqtt: CONNACK refused ({p!r})")
        # keep-alive discipline: CONNECT declared keep_alive, so a
        # spec-compliant broker drops us after 1.5x of idle — ping from
        # a background thread whenever no packet was sent for half of it
        self._last_send = time.monotonic()
        if keep_alive > 0:
            from ..obs import prof as _prof

            _prof.named_thread("edge-mqtt-keepalive", "",
                               self._keepalive_loop,
                               args=(keep_alive / 2.0,)).start()

    def _keepalive_loop(self, interval: float) -> None:
        while not self._closed.wait(min(interval / 4, 5.0)):
            if time.monotonic() - self._last_send >= interval:
                try:
                    self.ping()
                except OSError:
                    return

    def _send(self, pkt: bytes) -> None:
        with self._wlock:
            # _wlock exists to serialize whole packets onto the socket
            # (interleaved sendall would corrupt the MQTT framing); it
            # is a per-connection leaf never taken with another lock
            # nns-lint: disable=NNS602 -- write lock IS the packet
            # framing serialization point; nothing else nests under it
            self._sock.sendall(pkt)
            self._last_send = time.monotonic()

    def publish(self, topic: str, payload: bytes,
                retain: bool = False) -> None:
        # retain bit (MQTT 3.1.1 §3.3.1.3): broker keeps the message and
        # delivers it to future subscribers — the discovery mechanism of
        # the hybrid connect type (server address survives the publish)
        self._send(_packet(_PUBLISH, 0x01 if retain else 0,
                           _mqtt_str(topic) + payload))

    @staticmethod
    def _parse_publish(flags: int, p: bytes) -> Tuple[str, bytes]:
        tlen = struct.unpack(">H", p[:2])[0]
        topic = p[2:2 + tlen].decode()
        i = 2 + tlen
        if (flags >> 1) & 0x03:  # QoS>0 carries a packet id
            i += 2
        return topic, p[i:]

    def subscribe(self, topic: str) -> None:
        var = struct.pack(">H", 1) + _mqtt_str(topic) + bytes([0])
        self._send(_packet(_SUBSCRIBE, 0x02, var))
        # the broker MAY deliver matching (e.g. retained) PUBLISHes
        # before the SUBACK (MQTT 3.1.1 §3.8.4): park them — without
        # bound, a wildcard against a populated broker can precede the
        # SUBACK with hundreds.  PINGRESPs from the keepalive thread are
        # ignored; only unexpected packet types count toward giving up,
        # and the socket timeout bounds the total wait.
        misc = 0
        while True:
            try:
                t, flags, p = _read_packet(self._sock)
            except socket.timeout as e:
                raise StreamError("mqtt: no SUBACK (timeout)") from e
            if t == _SUBACK:
                # payload: packet id (2) + per-topic return code; 0x80 =
                # subscription REFUSED (ACL / bad filter) — surfacing it
                # beats waiting forever for messages that never come
                if len(p) >= 3 and p[2] == 0x80:
                    raise StreamError(
                        f"mqtt: subscription to {topic!r} refused")
                return
            if t == _PUBLISH:
                self._pending.append(self._parse_publish(flags, p))
            elif t != _PINGRESP:
                misc += 1
                if misc > 8:
                    raise StreamError("mqtt: no SUBACK")

    def recv_publish(self) -> Optional[Tuple[str, bytes]]:
        """Next PUBLISH → (topic, payload); None on idle timeout.

        An idle timeout (no packet started) keeps the stream intact; a
        timeout MID-packet means the byte stream can no longer be
        resynchronized and the connection is declared dead."""
        if self._pending:
            return self._pending.pop(0)
        try:
            first = _read_exact(self._sock, 1)[0]
        except socket.timeout:
            return None  # idle: nothing started
        try:
            t, flags, p = _read_packet(self._sock, first_byte=first)
        except socket.timeout as e:
            raise ConnectionError(
                "mqtt: timed out mid-packet (stream desynced)") from e
        if t == _PINGRESP:
            return None
        if t != _PUBLISH:
            return None
        return self._parse_publish(flags, p)

    def set_recv_timeout(self, t: float) -> None:
        """Cap how long a single recv_publish may block (callers with a
        deadline shrink it to the remaining budget)."""
        self._sock.settimeout(max(0.05, t))

    def ping(self) -> None:
        self._send(_packet(_PINGREQ, 0, b""))

    def close(self) -> None:
        self._closed.set()
        try:
            self._send(_packet(_DISCONNECT, 0, b""))
        except OSError:
            pass
        self._sock.close()


class MiniBroker:
    """In-process QoS0 broker for loopback pipelines and tests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self._subs: Dict[socket.socket, List[str]] = {}
        # topic → retained PAYLOAD (parsed, not raw wire bytes: a QoS>0
        # publish carries a packet id that must not leak into QoS0
        # re-delivery), delivered on subscribe; empty payload clears the
        # slot, per spec
        self._retained: Dict[str, bytes] = {}
        # per-socket write locks: concurrent sendall calls from several
        # _serve threads would interleave packet bytes mid-stream
        self._wlocks: Dict[socket.socket, threading.Lock] = {}
        self._lock = threading.Lock()
        self._running = True
        from ..obs import prof as _prof

        self._thread = _prof.named_thread(
            "edge-mqtt-broker", str(self.port), self._accept_loop)
        self._thread.start()

    @staticmethod
    def _match(pattern: str, topic: str) -> bool:
        if pattern == "#":
            return True
        pp, tp = pattern.split("/"), topic.split("/")
        for i, seg in enumerate(pp):
            if seg == "#":
                return True
            if i >= len(tp) or (seg != "+" and seg != tp[i]):
                return False
        return len(pp) == len(tp)

    def _accept_loop(self) -> None:
        try:
            self._srv.settimeout(0.2)
        except OSError:
            return  # stop() closed the socket before the thread got here
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            from ..obs import prof as _prof

            _prof.named_thread("edge-mqtt-serve", "", self._serve,
                               args=(conn,)).start()

    def _send_pkt(self, conn: socket.socket, pkt: bytes) -> None:
        with self._lock:
            lock = self._wlocks.setdefault(conn, threading.Lock())
        with lock:
            # per-connection write lock: the broker's only job under it
            # is pushing one framed packet; serialization is the point
            # nns-lint: disable=NNS602 -- per-conn write leaf lock;
            # sendall under it IS the packet serialization
            conn.sendall(pkt)

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while True:
                try:
                    t, flags, p = _read_packet(conn)
                except socket.timeout:
                    if not self._running:
                        return
                    continue
                if t == _CONNECT:
                    self._send_pkt(conn, _packet(_CONNACK, 0, b"\x00\x00"))
                    with self._lock:
                        self._subs.setdefault(conn, [])
                elif t == _SUBSCRIBE:
                    pid = p[:2]
                    tlen = struct.unpack(">H", p[2:4])[0]
                    topic = p[4:4 + tlen].decode()
                    with self._lock:
                        self._subs.setdefault(conn, []).append(topic)
                        retained = [(tp, pl) for tp, pl
                                    in self._retained.items()
                                    if self._match(topic, tp)]
                    self._send_pkt(conn, _packet(_SUBACK, 0, pid + b"\x00"))
                    for tp, pl in retained:
                        self._send_pkt(conn, _packet(
                            _PUBLISH, 0x01, _mqtt_str(tp) + pl))
                elif t == _PUBLISH:
                    topic, payload = MqttClient._parse_publish(flags, p)
                    if flags & 0x01:  # retain
                        with self._lock:
                            if payload:
                                self._retained[topic] = payload
                            else:
                                self._retained.pop(topic, None)
                    with self._lock:
                        targets = [c for c, pats in self._subs.items()
                                   if c is not conn and any(
                                       self._match(pt, topic)
                                       for pt in pats)]
                    # rebuild canonically as QoS0: forwarding the raw
                    # var-payload of a QoS1 publish would prepend its
                    # packet id to every subscriber's payload
                    pkt = _packet(_PUBLISH, 0, _mqtt_str(topic) + payload)
                    for c in targets:
                        try:
                            self._send_pkt(c, pkt)
                        except OSError:
                            pass
                elif t == _PINGREQ:
                    self._send_pkt(conn, _packet(_PINGRESP, 0, b""))
                elif t == _DISCONNECT:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                self._subs.pop(conn, None)
                self._wlocks.pop(conn, None)
            conn.close()

    def stop(self) -> None:
        self._running = False
        self._srv.close()
        self._thread.join(timeout=3)


# -- buffer (de)serialization ------------------------------------------------

_MAX_MEMS = 16          # parity: GST_MQTT_MAX_NUM_MEMS
_CAPS_STR_LEN = 512     # parity: GST_MQTT_MAX_LEN_GST_CAPS_STR
_NONE = (1 << 64) - 1
_HDR_FMT = "<I" + "Q" * _MAX_MEMS + "qqQQQ" + f"{_CAPS_STR_LEN}s"
_HDR_SIZE = struct.calcsize(_HDR_FMT)


def pack_mqtt_buffer(buf: Buffer, caps: Optional[Caps],
                     base_epoch_us: int, now_us: int) -> bytes:
    payloads = [t.tobytes() for t in buf.tensors[:_MAX_MEMS]]
    sizes = [len(p) for p in payloads] + [0] * (_MAX_MEMS - len(payloads))
    caps_b = (str(caps) if caps is not None else "").encode()[
        :_CAPS_STR_LEN - 1]
    hdr = struct.pack(
        _HDR_FMT, len(payloads), *sizes, base_epoch_us, now_us,
        buf.duration if buf.duration is not None else _NONE,
        _NONE,  # dts: unused in this runtime
        buf.pts if buf.pts is not None else _NONE,
        caps_b)
    return hdr + b"".join(payloads)


def unpack_mqtt_buffer(data: bytes) -> Tuple[Buffer, Optional[TensorsSpec],
                                             int]:
    """→ (buffer, spec-from-caps, sent_epoch_us)."""
    if len(data) < _HDR_SIZE:
        raise StreamError(f"mqtt: short message ({len(data)}B)")
    fields = struct.unpack(_HDR_FMT, data[:_HDR_SIZE])
    num = fields[0]
    sizes = fields[1:1 + _MAX_MEMS]
    if num > _MAX_MEMS:
        raise StreamError(f"mqtt: header claims {num} memories (max "
                          f"{_MAX_MEMS})")
    if _HDR_SIZE + sum(sizes[:num]) > len(data):
        raise StreamError("mqtt: payload shorter than declared sizes")
    _base_us, sent_us = fields[1 + _MAX_MEMS], fields[2 + _MAX_MEMS]
    duration, _dts, pts = fields[3 + _MAX_MEMS:6 + _MAX_MEMS]
    caps_str = fields[6 + _MAX_MEMS].split(b"\x00", 1)[0].decode()
    spec = None
    if caps_str:
        from ..runtime.parser import parse_caps_string

        try:
            spec = parse_caps_string(caps_str).to_spec()
        except Exception:  # noqa: BLE001 — foreign caps: payload still flows
            spec = None
    tensors = []
    off = _HDR_SIZE
    for i in range(num):
        raw = np.frombuffer(data, np.uint8, count=sizes[i], offset=off)
        off += sizes[i]
        if spec is not None and i < len(spec.tensors):
            ts = spec.tensors[i]
            tensors.append(Tensor(
                raw.view(ts.dtype.np_dtype).reshape(ts.shape), ts))
        else:
            tensors.append(Tensor(raw, TensorSpec.from_shape(
                raw.shape, np.uint8)))
    return Buffer(
        tensors=tensors,
        pts=None if pts == _NONE else pts,
        duration=None if duration == _NONE else duration,
        format=spec.format if spec is not None else TensorFormat.STATIC,
    ), spec, sent_us


# -- elements ----------------------------------------------------------------


@register_element("mqttsink")
class MqttSink(SinkElement):
    FACTORY = "mqttsink"

    def __init__(self, name=None, host: str = "127.0.0.1", port: int = 1883,
                 pub_topic: str = "", client_id: str = "",
                 mqtt_qos: int = 0, num_buffers: int = -1,
                 epoch_fn: Optional[Callable[[], int]] = None,
                 reconnect: bool = True,
                 reconnect_timeout_s: float = 30.0, **props):
        self.host, self.port = host, port
        self.pub_topic = pub_topic
        self.client_id = client_id
        self.mqtt_qos = mqtt_qos
        self.num_buffers = num_buffers
        # pluggable clock (reference: NTP-disciplined epoch, ntputil.c)
        self.epoch_fn = epoch_fn
        # broker outages re-dial through the shared backoff/breaker
        # policy; past reconnect-timeout-s the outage becomes a clean
        # bus error instead of an eternal silent drop
        self.reconnect = reconnect
        self.reconnect_timeout_s = reconnect_timeout_s
        super().__init__(name, **props)
        self._client: Optional[MqttClient] = None
        self._base_us = 0
        self._sent = 0
        self._stopping = threading.Event()
        self._retry = RetryPolicy(name=self.name, base_s=0.2, max_s=2.0,
                                  fail_threshold=6, open_s=2.0)

    def _epoch_us(self) -> int:
        return int(self.epoch_fn()) if self.epoch_fn else \
            int(time.time() * 1e6)

    def start(self) -> None:
        cid = self.client_id or f"{os.uname().nodename}_{os.getpid()}_sink"
        topic = self.pub_topic or f"{cid}/topic"
        self.pub_topic = topic
        self._cid = cid
        self._stopping.clear()
        self._retry.metrics = LinkMetrics.get(
            self.name, f"{self.host}:{self.port}", kind="mqtt-pub")
        self._retry._sync_metrics()
        self._client = MqttClient(self.host, self.port, cid)
        self._base_us = self._epoch_us()
        self._sent = 0

    def render(self, buf: Buffer) -> None:
        n = int(self.num_buffers)
        if n >= 0 and self._sent >= n:
            return
        caps = self.sinkpad.caps
        data = pack_mqtt_buffer(buf, caps, self._base_us, self._epoch_us())
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is not None:
            # trace context rides a magic-framed trailer AFTER the
            # payload; pre-trace subscribers parse by the header's
            # declared sizes and never see it (obs.tracectx)
            data = tracectx.append_trailer(
                data, tracectx.oneway_ctx(tr, self._epoch_us()))
        try:
            self._client.publish(str(self.pub_topic), data)
        except (ConnectionError, OSError) as e:
            if not bool(self.reconnect):
                raise
            self._retry.failure(e, what="broker publish")
            self._republish(data)
        self._sent += 1

    def _republish(self, data: bytes) -> None:
        """Broker gone mid-stream: reconnect through the shared retry
        policy and re-publish the frame.  Blocking here IS the
        backpressure — the streaming thread holds the frame until the
        broker answers, stop() interrupts, or the outage exceeds
        ``reconnect-timeout-s`` (→ StreamError on the bus via the chain
        guard)."""
        try:
            self._client.close()
        except OSError:
            pass
        deadline = time.monotonic() + float(self.reconnect_timeout_s)
        while not self._stopping.is_set():
            if time.monotonic() >= deadline:
                raise StreamError(
                    f"{self.name}: broker unreachable for "
                    f"{self.reconnect_timeout_s}s (gave up reconnecting)")
            if not self._retry.wait(stop=self._stopping, max_s=max(
                    deadline - time.monotonic(), 0.05)):
                return
            try:
                client = MqttClient(self.host, self.port, self._cid)
                client.publish(str(self.pub_topic), data)
            except (ConnectionError, OSError, StreamError) as e:
                self._retry.failure(e, what="broker reconnect")
                continue
            self._client = client
            self._retry.success()
            m = self._retry.metrics
            if m is not None:
                m.reconnect()
            return

    def stop(self) -> None:
        self._stopping.set()
        if self._client is not None:
            self._client.close()
            self._client = None


@register_element("mqttsrc")
class MqttSrc(SourceElement):
    FACTORY = "mqttsrc"

    def __init__(self, name=None, host: str = "127.0.0.1", port: int = 1883,
                 sub_topic: str = "", client_id: str = "",
                 num_buffers: int = -1, sub_timeout: float = 10.0,
                 reconnect: bool = True,
                 reconnect_timeout_s: float = 30.0, **props):
        self.host, self.port = host, port
        self.sub_topic = sub_topic
        self.client_id = client_id
        self.num_buffers = num_buffers
        self.sub_timeout = sub_timeout
        # a broker outage re-dials + re-subscribes through the shared
        # backoff/breaker policy (the old behavior — give up and EOS on
        # the first ConnectionError — hid broker restarts as silent
        # stream ends); past reconnect-timeout-s it becomes a clean bus
        # error
        self.reconnect = reconnect
        self.reconnect_timeout_s = reconnect_timeout_s
        super().__init__(name, **props)
        self._client: Optional[MqttClient] = None
        self._rx: "_q.Queue" = _q.Queue(maxsize=256)
        self._thread: Optional[threading.Thread] = None
        self._count = 0
        self.last_latency_us: Optional[int] = None
        self._retry = RetryPolicy(name=self.name, base_s=0.2, max_s=2.0,
                                  fail_threshold=6, open_s=2.0)

    def output_spec(self) -> TensorsSpec:
        # schema rides in each message's caps header: flexible stream
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def output_caps(self) -> Caps:
        return Caps.from_spec(self.output_spec())

    def start(self) -> None:
        if not self.sub_topic:
            raise StreamError(f"{self.name}: sub-topic not set")
        self._cid = self.client_id or \
            f"{os.uname().nodename}_{os.getpid()}_src"
        self._retry.metrics = LinkMetrics.get(
            self.name, f"{self.host}:{self.port}", kind="mqtt-sub")
        self._retry._sync_metrics()
        self._client = self._connect_broker()
        self._count = 0
        # the source thread (and _running) must exist BEFORE the rx
        # loop: its reconnect gate reads _running, and a broker that
        # dies immediately after the subscribe would otherwise be
        # misread as "stopping" and silently EOS the stream
        super().start()
        from ..obs import prof as _prof

        self._thread = _prof.named_thread(
            "edge-mqtt-rx", self.name, self._rx_loop)
        self._thread.start()

    def _connect_broker(self) -> MqttClient:
        client = MqttClient(self.host, self.port, self._cid,
                            timeout=float(self.sub_timeout))
        client.subscribe(str(self.sub_topic))
        return client

    def _rx_loop(self) -> None:
        while self._client is not None:
            try:
                msg = self._client.recv_publish()
            except (ConnectionError, OSError) as e:
                if not bool(self.reconnect) \
                        or not self._running.is_set():
                    self._rx.put(None)
                    return
                self._retry.failure(e, what="broker connection")
                if not self._reconnect_broker():
                    self._rx.put(None)
                    return
                continue
            if msg is not None:
                self._rx.put(msg[1])

    def _reconnect_broker(self) -> bool:
        """Re-dial + re-subscribe through the shared retry policy.
        False when stop() interrupted or the outage outlived
        ``reconnect-timeout-s`` (the give-up posts a bus error — the
        stream ends loudly, never silently)."""
        old, self._client = self._client, None
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        deadline = time.monotonic() + float(self.reconnect_timeout_s)
        while self._running.is_set():
            if time.monotonic() >= deadline:
                self.post_error(StreamError(
                    f"{self.name}: broker unreachable for "
                    f"{self.reconnect_timeout_s}s (gave up reconnecting)"))
                return False
            self._retry.wait(max_s=max(deadline - time.monotonic(), 0.05))
            if not self._running.is_set():
                return False
            try:
                client = self._connect_broker()
            except (ConnectionError, OSError, StreamError) as e:
                self._retry.failure(e, what="broker reconnect")
                continue
            self._client = client
            self._retry.success()
            m = self._retry.metrics
            if m is not None:
                m.reconnect()
            return True
        return False

    def create(self) -> Optional[Buffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        while self._running.is_set():
            try:
                data = self._rx.get(timeout=0.05)
            except _q.Empty:
                continue
            if data is None:
                return None
            data, ctx = tracectx.split_trailer(data)
            buf, _spec, sent_us = unpack_mqtt_buffer(data)
            self.last_latency_us = int(time.time() * 1e6) - sent_us
            if ctx is not None and _hooks.tracer is not None:
                tracectx.plant_oneway(buf.meta, ctx,
                                      int(time.time() * 1e6),
                                      link=self.name,
                                      source_name=self.name)
            self._count += 1
            return buf
        return None

    def stop(self) -> None:
        super().stop()
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None

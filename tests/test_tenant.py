"""`obs/tenantstat.py` + the `tenant=` stream property (ISSUE-19
surface).

The EXACT integer-nanosecond device-time split (unit + many-window
drift test), frames-only attribution on unsampled dispatches,
scrape-time dollar derivation (`NNS_TPU_CHIP_HOUR_USD` re-pricing
history without rewriting it), per-tenant SLO attainment and shed
accounting, end-to-end attribution through real share-model pipelines
(the exactness invariant against the pool's own clock reads), the
snapshot-v9 `tenants` table + `nns_tenant_*` families, the
register/scrape-vs-record race, tenant-scoped playbook targeting, and
the nns-top TENANT section."""

import json
import random
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import (JaxXlaFilter,
                                            register_model,
                                            unregister_model)
from nnstreamer_tpu.obs.metrics import REGISTRY
from nnstreamer_tpu.obs.tenantstat import (DEFAULT_TENANT, TENANT_STATS,
                                           TenantStats)
from nnstreamer_tpu.runtime import MODEL_POOL, Pipeline

SHAPE = (4,)
SPEC = TensorsSpec.from_shapes([SHAPE], np.float32)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_tenant", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_tenant")


@pytest.fixture(autouse=True)
def _clean():
    TENANT_STATS.reset()
    yield
    TENANT_STATS.reset()
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()


# -- the exact split (unit) ---------------------------------------------------


def test_record_window_splits_device_ns_exactly():
    st = TenantStats()
    st.record_window("pl", {"a": 3, "b": 2, "c": 1}, device_ns=1000003)
    tenant_ns, pool_ns = st.exactness("pl")
    assert tenant_ns == pool_ns == 1000003
    rows = {r["tenant"]: r for r in st.snapshot()}
    # proportional shares, integer residual parked on the largest
    # tenant (3/6 of 1000003 = 500001 floor + residual)
    assert rows["b"]["device_seconds"] == pytest.approx(
        (1000003 * 2 // 6) / 1e9)
    assert rows["c"]["device_seconds"] == pytest.approx(
        (1000003 // 6) / 1e9)
    assert rows["a"]["frames"] == 3 and rows["c"]["frames"] == 1


def test_exactness_never_drifts_over_many_windows():
    """The invariant is per-dispatch AND cumulative: thousands of
    ragged windows with awkward primes must keep the tenant sum equal
    to the pool total to the nanosecond."""
    st = TenantStats()
    rng = random.Random(19)
    total = 0
    for _ in range(2000):
        frames = {t: rng.randint(0, 7)
                  for t in ("alpha", "beta", "gamma", "default")}
        if not any(frames.values()):
            frames["alpha"] = 1
        ns = rng.choice((0, 1, 997, 65537, 1000000007))
        st.record_window("pl", frames, device_ns=ns)
        total += ns
    tenant_ns, pool_ns = st.exactness("pl")
    assert tenant_ns == pool_ns == total


def test_unsampled_windows_count_frames_not_time():
    st = TenantStats()
    st.record_window("pl", {"a": 4}, device_ns=None)
    st.record_window("pl", {"": 2}, device_ns=None)  # "" -> default
    assert st.exactness("pl") == (0, 0)
    rows = {r["tenant"]: r for r in st.snapshot()}
    assert rows["a"]["frames"] == 4
    assert rows[DEFAULT_TENANT]["frames"] == 2
    assert rows["a"]["device_seconds"] == 0.0
    # an all-zero window is a no-op, not a row
    st.record_window("pl", {"z": 0}, device_ns=123)
    assert "z" not in {r["tenant"] for r in st.snapshot()}


def test_dollars_derive_at_scrape_time(monkeypatch):
    """Attribution stores time, never money: re-pricing via the env
    override re-prices ALL history on the next scrape without a single
    new window."""
    st = TenantStats()
    st.record_window("pl", {"a": 1}, device_ns=3_600_000_000_000)  # 1 chip-hour
    monkeypatch.setenv("NNS_TPU_CHIP_HOUR_USD", "2.5")
    (row,) = st.snapshot()
    assert row["dollars"] == pytest.approx(2.5)
    monkeypatch.setenv("NNS_TPU_CHIP_HOUR_USD", "10")
    (row,) = st.snapshot()
    assert row["dollars"] == pytest.approx(10.0)
    # a malformed override must not break the scrape (price falls back)
    monkeypatch.setenv("NNS_TPU_CHIP_HOUR_USD", "not-a-price")
    (row,) = st.snapshot()
    assert row["dollars"] >= 0.0


def test_slo_attainment_and_shed_accounting():
    st = TenantStats()
    for lat in (0.01, 0.02, 0.5):
        st.record_latency("pl", "a", lat, slo_s=0.1)
    st.record_shed("pl", "a", "slo", frames=3)
    st.record_shed("pl", "a", "queue-full")
    (row,) = st.snapshot()
    assert row["slo_attainment"] == pytest.approx(2.0 / 3.0)
    assert row["slo_frames"] == 3
    assert row["shed"] == {"slo": 3, "queue-full": 1}
    # a tenant with no graded frames reports None, not a fake 100%
    st.record_window("pl", {"quiet": 1})
    quiet = [r for r in st.snapshot() if r["tenant"] == "quiet"][0]
    assert quiet["slo_attainment"] is None


# -- end to end through real share-model pipelines ----------------------------


def _tenant_pipe(tag, tenant, batch=8):
    p = Pipeline(name=f"ten_{tag}")
    src = AppSrc(name="src", spec=SPEC, max_buffers=128)
    q = Queue(name="q", max_size_buffers=128)
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="_t_tenant", batch=batch,
                       batch_timeout_ms=5.0, batch_buckets=str(batch),
                       share_model=True, tenant=tenant,
                       stat_sample_interval_ms=0.0)
    sink = AppSink(name="sink", max_buffers=128)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


def test_pipeline_attribution_exact_and_snapshot_v9():
    """Three tenants (one implicit default) sharing one pool: every
    frame lands in some tenant's row, the device-ns split sums EXACTLY
    to the pool's own accumulator, and the v9 snapshot carries the
    rows + the flat `nns_tenant_*` families."""
    n = 48
    pipes = [_tenant_pipe("a", "alpha"), _tenant_pipe("b", "beta"),
             _tenant_pipe("d", "")]
    for p, *_ in pipes:
        p.start()
    label = pipes[0][2].pool.label()

    def produce(src):
        for i in range(n):
            src.push_buffer(Buffer.of(
                np.full(SHAPE, float(i), np.float32), pts=i))
        src.end_of_stream()

    threads = [threading.Thread(target=produce, args=(src,))
               for _p, src, _f, _s in pipes]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p, *_ in pipes:
        assert p.wait_eos(timeout=30)
    try:
        tenant_ns, pool_ns = TENANT_STATS.exactness(label)
        assert tenant_ns == pool_ns
        assert pool_ns > 0  # every dispatch sampled: honest device time
        rows = {r["tenant"]: r
                for r in TENANT_STATS.snapshot() if r["pool"] == label}
        assert set(rows) == {"alpha", "beta", DEFAULT_TENANT}
        assert all(r["frames"] == n for r in rows.values())
        snap = REGISTRY.snapshot()
        assert snap["version"] == 10
        tab = [r for r in snap["tenants"] if r["pool"] == label]
        assert [r["tenant"] for r in tab] \
            == sorted(r["tenant"] for r in tab)
        fams = snap["metrics"]
        seconds = {s["labels"]["tenant"]: s["value"] for s in
                   fams["nns_tenant_device_seconds_total"]["samples"]
                   if s["labels"]["pool"] == label}
        assert sum(seconds.values()) == pytest.approx(pool_ns / 1e9)
        frames = {s["labels"]["tenant"]: s["value"] for s in
                  fams["nns_tenant_frames_total"]["samples"]
                  if s["labels"]["pool"] == label}
        assert frames == {"alpha": n, "beta": n, DEFAULT_TENANT: n}
        assert "nns_tenant_dollars_total" in fams
        json.dumps(snap["tenants"])  # wire-safe
    finally:
        for p, *_ in pipes:
            p.stop()


def test_tenant_register_scrape_race():
    """Three threads — a scraper snapshotting the registry, a dispatch
    recorder, an admission recorder — against pipeline start/stop
    churn: no exception, and the exactness invariant holds at the
    end (same stop-vs-scrape discipline as the PR-10/11 races)."""
    stop = threading.Event()
    errors = []

    def scraper():
        try:
            while not stop.is_set():
                snap = REGISTRY.snapshot()
                json.dumps(snap["tenants"])
        except Exception as e:  # noqa: BLE001 - the assert is the point
            errors.append(e)

    def dispatcher():
        try:
            i = 0
            while not stop.is_set():
                TENANT_STATS.record_window(
                    "race-pool", {"a": 1 + i % 3, "b": 2}, device_ns=997)
                i += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def admitter():
        try:
            while not stop.is_set():
                TENANT_STATS.record_latency("race-pool", "a", 0.01, 0.1)
                TENANT_STATS.record_shed("race-pool", "b", "slo")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=f)
               for f in (scraper, dispatcher, admitter)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert errors == []
    tenant_ns, pool_ns = TENANT_STATS.exactness("race-pool")
    assert tenant_ns == pool_ns > 0


# -- tenant-scoped playbooks --------------------------------------------------


def test_playbook_targets_only_its_tenant():
    """A tenant-scoped playbook fires only when the offending series
    names ITS tenant — the other tenant's burn must not throttle it."""
    from nnstreamer_tpu.obs.control import Controller, Playbook

    class _StubWatch:
        def __init__(self, tenant):
            self.tenant = tenant

        def alerts(self):
            return [{"rule": "tenant-burn", "firing": True,
                     "severity": "warning",
                     "detail": {"metric": "nns_tenant_shed_total",
                                "value": 1.0,
                                "series": {"pool": "pl",
                                           "tenant": self.tenant}}}]

    pb = Playbook(name="throttle-alpha", rule="tenant-burn",
                  kind="pool", actuator="ramp-start", action="set",
                  value=0.5, tenant="alpha", cooldown_s=0.0)
    ctl = Controller(playbooks=[pb], watch=_StubWatch("beta"))
    assert ctl.tick() == []  # beta's burn is not alpha's problem
    ctl2 = Controller(playbooks=[pb], watch=_StubWatch("alpha"))
    decisions = ctl2.tick()
    assert len(decisions) == 1  # fired (no live pool -> no-target)
    assert decisions[0]["playbook"] == "throttle-alpha"


# -- nns-top ------------------------------------------------------------------


def test_top_tenant_section_renders():
    from nnstreamer_tpu.obs.top import render

    TENANT_STATS.record_window("pl", {"alpha": 3, "beta": 1},
                               device_ns=4_000_000)
    TENANT_STATS.record_latency("pl", "alpha", 0.01, 0.1)
    TENANT_STATS.record_shed("pl", "beta", "slo", frames=2)
    out = render(REGISTRY.snapshot())
    assert "TENANT" in out
    assert "alpha" in out and "beta" in out
    # rate column needs a prev snapshot; without one it renders dashes
    assert "$/KFRM" in out

"""Per-filter latency/throughput instrumentation.

Parity target: /root/reference/gst/nnstreamer/tensor_filter/tensor_filter.c:366-468
— rolling window of recent invoke latencies (GST_TF_STAT_MAX_RECENT = 10),
overflow-safe accumulators, throughput as 1000×FPS integer, and LATENCY
reporting with 5% headroom / 25% update threshold (tensor_filter.c:109-120).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

STAT_MAX_RECENT = 10
LATENCY_REPORT_HEADROOM = 1.05   # 5% headroom on reported latency
LATENCY_REPORT_THRESHOLD = 0.25  # re-report when moving beyond ±25%


class InvokeStats:
    """Thread-safe rolling invoke statistics.

    With micro-batching (``runtime/batching.py``) one *invoke* (XLA
    dispatch) can carry several *frames*; ``record``/``count`` take the
    per-invoke frame count so the stats report both frames/s
    (:attr:`throughput_milli_fps`) and dispatches/s
    (:attr:`dispatch_milli_fps`), plus the realized batch occupancy.
    Unbatched callers (frames=1) see the exact pre-batching numbers.

    With the shared-model serving pool (``runtime/serving.py``) one
    dispatch can additionally carry frames from several *pipelines*:
    ``streams`` is the number of distinct streams contributing to the
    dispatch, accumulated into :attr:`avg_stream_occupancy` (the
    cross-stream coalescing measure), and :attr:`attached_streams` is a
    gauge of how many streams are currently attached to the pool entry.
    """

    def __init__(self, window: int = STAT_MAX_RECENT):
        self._lock = threading.Lock()
        self._recent = collections.deque(maxlen=window)
        self.total_invoke_num = 0   # dispatches
        self.total_frame_num = 0    # frames carried by those dispatches
        self.total_stream_num = 0   # distinct streams, summed per dispatch
        self.attached_streams = 0   # gauge: streams on the pool entry
        self.total_invoke_latency_us = 0  # accumulated, overflow-free (py int)
        self._first_ts: Optional[float] = None
        self._first_frames = 0  # frames carried by the first dispatch
        self._last_ts: Optional[float] = None
        self._last_reported_us: Optional[float] = None
        # dispatch cost attribution (sampled dispatches only): rolling
        # window of (host-prep, device, host-drain) seconds plus
        # cumulative totals — the boundaries are block_until_ready
        # fences, so prep + device equals the recorded invoke latency
        # and prep + device + drain partitions the whole dispatch
        self._phase_recent = collections.deque(maxlen=window)
        self.phase_samples = 0
        self.total_host_prep_s = 0.0
        self.total_device_s = 0.0
        self.total_host_drain_s = 0.0

    def _tick(self, frames: int, streams: int) -> None:
        """Bump invoke count + first/last timestamps (callers hold _lock)."""
        now = time.monotonic()
        self.total_invoke_num += 1
        self.total_frame_num += max(int(frames), 1)
        self.total_stream_num += max(int(streams), 1)
        if self._first_ts is None:
            self._first_ts = now
            self._first_frames = max(int(frames), 1)
        self._last_ts = now

    def record(self, latency_s: float, frames: int = 1,
               streams: int = 1) -> None:
        us = latency_s * 1e6
        with self._lock:
            self._recent.append(us)
            self.total_invoke_latency_us += int(us)
            self._tick(frames, streams)

    def count(self, frames: int = 1, streams: int = 1) -> None:
        """Count an invoke without a latency sample (async dispatch whose
        execution time is unknown) so throughput stays accurate while
        latency reflects only sampled, device-synchronized invokes."""
        with self._lock:
            self._tick(frames, streams)

    def record_phases(self, prep_s: float, device_s: float,
                      drain_s: float) -> None:
        """Record one sampled dispatch's host/device phase split:
        host-prep (input gather/convert/place), device (dispatch →
        ``block_until_ready``) and host-drain (output wrap/demux).
        Phases come from consecutive clock reads around one dispatch,
        so their sum IS the dispatch's wall time by construction."""
        with self._lock:
            self._phase_recent.append((prep_s, device_s, drain_s))
            self.phase_samples += 1
            self.total_host_prep_s += prep_s
            self.total_device_s += device_s
            self.total_host_drain_s += drain_s

    # -- unlocked readers (callers hold _lock) -------------------------------

    def _latency_us_locked(self) -> int:
        if not self._recent:
            return -1
        return int(sum(self._recent) / len(self._recent))

    def _throughput_milli_fps_locked(self) -> int:
        if (self.total_invoke_num < 2 or self._first_ts is None
                or self._last_ts is None or self._last_ts <= self._first_ts):
            return -1
        fps = (self.total_frame_num - self._first_frames) \
            / (self._last_ts - self._first_ts)
        return int(fps * 1000)

    def _dispatch_milli_fps_locked(self) -> int:
        if (self.total_invoke_num < 2 or self._first_ts is None
                or self._last_ts is None or self._last_ts <= self._first_ts):
            return -1
        dps = (self.total_invoke_num - 1) / (self._last_ts - self._first_ts)
        return int(dps * 1000)

    def _avg_batch_occupancy_locked(self) -> float:
        if self.total_invoke_num == 0:
            return 0.0
        return self.total_frame_num / self.total_invoke_num

    def _avg_stream_occupancy_locked(self) -> float:
        if self.total_invoke_num == 0:
            return 0.0
        return self.total_stream_num / self.total_invoke_num

    def _phase_means_us_locked(self):
        """Rolling-window mean of each phase in µs, or (-1,-1,-1) before
        the first sampled dispatch (same "no data yet" sentinel as
        :attr:`latency_us`)."""
        if not self._phase_recent:
            return -1, -1, -1
        n = len(self._phase_recent)
        prep = sum(p for p, _, _ in self._phase_recent) / n
        dev = sum(d for _, d, _ in self._phase_recent) / n
        drain = sum(d for _, _, d in self._phase_recent) / n
        return int(prep * 1e6), int(dev * 1e6), int(drain * 1e6)

    # -- public readers ------------------------------------------------------

    @property
    def latency_us(self) -> int:
        """Average invoke latency over the recent window, µs (parity:
        'latency' property, tensor_filter_common.c:982-988)."""
        with self._lock:
            return self._latency_us_locked()

    @property
    def throughput_milli_fps(self) -> int:
        """1000×FPS over the whole run, in FRAMES (parity: 'throughput'
        property, tensor_filter_common.c:989-996; identical to the
        dispatch rate when every invoke carries one frame).  The first
        dispatch's frames are excluded, mirroring the unbatched (N-1)
        events over (N-1) intervals accounting — else a 2-dispatch
        batched run would report nearly double its true rate."""
        with self._lock:
            return self._throughput_milli_fps_locked()

    @property
    def dispatch_milli_fps(self) -> int:
        """1000×dispatches/s — with micro-batching, the XLA invoke rate
        (< frame rate when coalescing is happening)."""
        with self._lock:
            return self._dispatch_milli_fps_locked()

    @property
    def avg_batch_occupancy(self) -> float:
        """Mean frames per dispatch (1.0 unbatched)."""
        with self._lock:
            return self._avg_batch_occupancy_locked()

    @property
    def avg_stream_occupancy(self) -> float:
        """Mean distinct streams contributing to one dispatch (1.0 for a
        single-pipeline filter; >1 exactly when the serving pool is
        coalescing across pipelines)."""
        with self._lock:
            return self._avg_stream_occupancy_locked()

    def snapshot(self) -> dict:
        """Every derived statistic as ONE consistent dict, read under a
        single lock acquisition — the poller API (`nns-top`, the obs
        metrics registry).  Reading the individual properties instead
        takes the lock once per field, so a dispatch landing between
        reads yields e.g. a frame total from one dispatch and a latency
        from the next."""
        with self._lock:
            prep_us, dev_us, drain_us = self._phase_means_us_locked()
            return {
                "invokes": self.total_invoke_num,
                "frames": self.total_frame_num,
                "latency_us": self._latency_us_locked(),
                "throughput_milli_fps": self._throughput_milli_fps_locked(),
                "dispatch_milli_fps": self._dispatch_milli_fps_locked(),
                "avg_batch_occupancy": self._avg_batch_occupancy_locked(),
                "avg_stream_occupancy": self._avg_stream_occupancy_locked(),
                "attached_streams": self.attached_streams,
                "host_prep_us": prep_us,
                "device_us": dev_us,
                "host_drain_us": drain_us,
                "phase": {
                    "samples": self.phase_samples,
                    "host_prep_s": self.total_host_prep_s,
                    "device_s": self.total_device_s,
                    "host_drain_s": self.total_host_drain_s,
                },
            }

    def latency_to_report(self) -> Optional[int]:
        """µs to report on the bus if it moved past the threshold, else None
        (parity: track_latency, tensor_filter.c:480-506).  The window
        mean is computed inside the same lock acquisition as the
        last-reported compare-and-swap — re-entering through the
        ``latency_us`` property would read one window and threshold
        against another when a concurrent ``record`` lands between."""
        with self._lock:
            cur = self._latency_us_locked()
            if cur < 0:
                return None
            last = self._last_reported_us
            if last is None or abs(cur - last) > last * LATENCY_REPORT_THRESHOLD:
                self._last_reported_us = cur
                return int(cur * LATENCY_REPORT_HEADROOM)
        return None


class CompileStats:
    """Process-wide XLA compile telemetry: one row per (framework,
    kind, bucket), where ``kind`` names the compile path — ``cold``
    (first configure), ``reshape`` (SET_INPUT_INFO recompile),
    ``reload`` (hot model swap), ``bucket`` (a micro-batch bucket
    executable).  ``seconds`` accumulates the trace/lower time spent at
    the compile site PLUS the executable's first invocation (jit
    compiles lazily — the first call is where XLA actually builds the
    program; on a non-trivial model that dwarfs the first execution).

    Pulled into the metrics registry at scrape time like every other
    collected stat (``nns_compiles_total`` / ``nns_compile_seconds_
    total``) and rendered as the COMPILE section of ``nns-top`` — the
    measurement substrate a persistent AOT compile cache will be
    judged against (ROADMAP item 4)."""

    def __init__(self):
        self._lock = threading.Lock()
        # (framework, kind, bucket) -> [count, seconds]
        self._rows: dict = {}

    def record(self, kind: str, seconds: float = 0.0, bucket: int = 0,
               framework: str = "jax-xla"):
        """Count one compile; returns the row key so the caller can
        attribute the executable's first-call time to the same row via
        :meth:`add_seconds`."""
        key = (str(framework), str(kind), str(int(bucket or 0)))
        with self._lock:
            row = self._rows.setdefault(key, [0, 0.0])
            row[0] += 1
            row[1] += float(seconds)
        return key

    def add_seconds(self, key, seconds: float) -> None:
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                row[1] += float(seconds)

    @property
    def total_compiles(self) -> int:
        with self._lock:
            return sum(r[0] for r in self._rows.values())

    @property
    def total_seconds(self) -> float:
        with self._lock:
            return sum(r[1] for r in self._rows.values())

    def snapshot(self) -> list:
        """Rows for the registry / nns-top: sorted, one dict per
        (framework, kind, bucket)."""
        with self._lock:
            return [{"framework": fw, "kind": kind, "bucket": bucket,
                     "count": row[0], "seconds": row[1]}
                    for (fw, kind, bucket), row
                    in sorted(self._rows.items())]

    def reset(self) -> None:
        """Tests/bench only: drop every row."""
        with self._lock:
            self._rows.clear()


#: the process-wide compile telemetry every framework sub-plugin feeds
COMPILE_STATS = CompileStats()


class DispatchStats:
    """Process-wide count of XLA program launches, by launch site
    (``filter`` / ``transform`` / ``decoder`` / ``decoder_pack``).

    This is the denominator-side witness of the fusion work
    (runtime/fusion.py): a fused transform→filter→decoder window is
    exactly ONE ``filter`` launch, while the unfused pipeline pays one
    launch per stage.  ``bench.py --composite`` gates
    ``dispatches_per_frame`` on a delta of :attr:`total` over a counted
    number of windows — which only works if every site that hands a
    program to XLA bumps the counter, so keep the call sites in sync
    with the ``site`` names above.  One short lock per dispatch; a
    dispatch costs orders of magnitude more than the bump."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: dict = {}

    def count(self, site: str, n: int = 1) -> None:
        with self._lock:
            self._sites[site] = self._sites.get(site, 0) + int(n)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._sites.values())

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._sites)

    def reset(self) -> None:
        """Tests/bench only."""
        with self._lock:
            self._sites.clear()


#: process-wide dispatch accounting (bench gate: dispatches_per_frame)
DISPATCH_STATS = DispatchStats()

"""``flexbuf`` decoder: tensors → FlexBuffers wire payloads.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-flexbuf.cc (235 LoC, mime ``other/flexbuf``): serializes the
tensor frame into one FlexBuffers map (``num_tensors``/``rate_n``/
``rate_d``/``format``/``tensor_#``) so the receiving side — the
``flexbuf`` converter sub-plugin here or the reference's
tensor_converter_flexbuf.cc — reconstructs it without out-of-band caps.
Codec shared with the converter via ``nnstreamer_tpu.converters.codecs``.
"""

from __future__ import annotations

from ..converters.codecs import flexbuf_encode
from . import register_decoder
from .wirefmt import _WireDecoder


@register_decoder
class FlexBuf(_WireDecoder):
    MODE = "flexbuf"
    MIME = "other/flexbuf"
    ENCODE = staticmethod(flexbuf_encode)

"""``jax-optax`` — the flagship trainer sub-plugin.

Where the reference's tensor_trainer hands samples to nntrainer on one
device (/root/reference/ext/nnstreamer/tensor_trainer/, consumed through
nnstreamer_plugin_api_trainer.h), this backend micro-batches the sample
stream and trains with the mesh-sharded optax step from
parallel/sharded.py: one jitted XLA computation per step spanning the
whole device mesh (data-parallel batch, tensor-parallel weight shards,
gradient all-reduce over ICI).

``model-config`` keys:

- ``apply``   — the model's apply fn: a callable, a ``"module:callable"``
  import path, or the name of a model registered with the jax-xla filter
- ``init``    — optional params source: a pytree, a callable
  ``init(rng) -> params``, or omitted when ``apply`` resolves to a
  registered model that carries params / ``model_load_path`` is set
- ``optimizer`` — ``"sgd"`` (default) / ``"adam"`` / ``"adamw"``
- ``lr``      — learning rate (default 1e-2)
- ``batch_size`` — micro-batch assembled from the sample stream
  (default 8; rounded up to a multiple of the data-axis size)
- ``mesh``    — mesh spec string, default ``"data:-1"``
- ``seed``    — PRNG seed for init (default 0)

Training runs on a worker thread so ``push_data`` only blocks when the
sample queue is full (backpressure), mirroring the reference's async
sub-plugin contract.  The saved model is a ``.pkl`` params-file directly
loadable by the jax-xla filter (``model=<path>.pkl``) — train in a
pipeline, serve in a pipeline.
"""

from __future__ import annotations

import importlib
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerError,
    TrainerProps,
    TrainerSubplugin,
    register_trainer,
)


def _resolve_apply(cfg: Dict, load_path: str) -> Tuple[Any, Any, str]:
    """Returns (apply_fn, params_or_None, apply_import_path_or_empty)."""
    apply = cfg.get("apply")
    params = cfg.get("init")
    apply_path = ""
    if isinstance(apply, str) and ":" in apply:
        mod, _, attr = apply.partition(":")
        try:
            fn = getattr(importlib.import_module(mod), attr)
        except (ImportError, AttributeError) as e:
            raise TrainerError(
                f"jax-optax: cannot resolve apply {apply!r}: {e}") from e
        apply_path = apply
    elif isinstance(apply, str):
        from ..filters.jax_xla import get_model

        m = get_model(apply)
        if m is None:
            raise TrainerError(
                f"jax-optax: {apply!r} is neither an import path nor a "
                "registered model")
        fn, params = m.fn, params if params is not None else m.params
    elif callable(apply):
        fn = apply
    else:
        raise TrainerError("jax-optax: model-config needs an 'apply'")
    if load_path:
        from .checkpoint import is_orbax_path, load_orbax

        if is_orbax_path(load_path):
            params = load_orbax(load_path,
                                template=params if params is not None
                                else None)
        else:
            import pickle

            with open(load_path, "rb") as f:
                blob = pickle.load(f)
            params = blob["params"] if isinstance(blob, dict) and \
                "params" in blob else blob
    if callable(params):
        import jax

        params = params(jax.random.PRNGKey(int(cfg.get("seed", 0))))
    return fn, params, apply_path


def _make_optimizer(cfg: Dict):
    import optax

    lr = float(cfg.get("lr", 1e-2))
    name = str(cfg.get("optimizer", "sgd")).lower()
    if name == "sgd":
        return optax.sgd(lr, momentum=float(cfg.get("momentum", 0.9)))
    if name == "adam":
        return optax.adam(lr)
    if name == "adamw":
        return optax.adamw(lr)
    raise TrainerError(f"jax-optax: unknown optimizer {name!r}")


@register_trainer
class JaxOptaxTrainer(TrainerSubplugin):
    NAME = "jax-optax"

    def __init__(self):
        super().__init__()
        self._cfg: Dict = {}
        self._queue: "queue.Queue" = queue.Queue(maxsize=256)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._status_lock = threading.Lock()
        self._status = {"epoch": 0.0, "training_loss": 0.0,
                        "training_accuracy": 0.0, "validation_loss": 0.0,
                        "validation_accuracy": 0.0}
        self._apply = None
        self._params = None
        self._apply_path = ""
        self._sample_shape = None  # (shape, dtype) of one input sample

    # -- lifecycle ------------------------------------------------------------

    def configure(self, props: TrainerProps, notify) -> None:
        super().configure(props, notify)
        cfg = props.model_config
        if isinstance(cfg, str):
            import json

            with open(cfg) as f:
                cfg = json.load(f)
        if not isinstance(cfg, dict):
            raise TrainerError(
                "jax-optax: model-config must be a dict or a JSON path")
        self._cfg = cfg
        self._apply, self._params, self._apply_path = _resolve_apply(
            cfg, props.model_load_path)
        if self._params is None:
            raise TrainerError(
                "jax-optax: no params — provide 'init' in model-config, a "
                "registered model with params, or model-load-path")

    def start(self) -> None:
        self._stop_evt.clear()
        self.finished.clear()
        from ..obs import prof as _prof

        self._thread = _prof.named_thread(
            "train", f"optax:{self.NAME}", self._train_loop)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    # -- data feed ------------------------------------------------------------

    def push_data(self, inputs: List, labels: List,
                  is_validation: bool = False) -> None:
        if self.error is not None:
            raise TrainerError(
                f"jax-optax: training failed: {self.error}")
        x = np.asarray(inputs[0])
        if x.ndim > 1 and x.shape[0] == 1:
            x = x[0]  # stream buffers carry a leading frame dim of 1
        y = np.asarray(labels[0]).reshape(-1)
        y = y[0] if y.size == 1 else y  # class index label
        while not self._stop_evt.is_set():
            try:
                self._queue.put((x, y, is_validation), timeout=0.5)
                return
            except queue.Full:
                continue  # backpressure: block the streaming thread

    def get_status(self) -> Dict[str, float]:
        with self._status_lock:
            return dict(self._status)

    def save(self, path: str) -> None:
        from ..filters.jax_xla import save_params_model
        from .checkpoint import is_orbax_path, save_orbax

        if is_orbax_path(path):
            save_orbax(path, self._params)
            return
        if not self._apply_path:
            raise TrainerError(
                "jax-optax: saving needs 'apply' as a \"module:callable\" "
                "import path so the saved model is loadable by the "
                "jax-xla filter")
        in_shapes = in_dtypes = None
        if self._sample_shape is not None:
            shape, dtype = self._sample_shape
            in_shapes, in_dtypes = [(1, *shape)], dtype
        save_params_model(path, self._apply_path, self._params,
                          in_shapes=in_shapes, in_dtypes=in_dtypes)

    # -- training loop --------------------------------------------------------

    def _mesh_and_step(self, example_x, example_y):
        import jax

        from ..parallel import make_mesh, train_step

        mesh_spec = str(self._cfg.get("mesh", "data:-1"))
        mesh = make_mesh(mesh_spec)
        data_axis = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
            "data", 1)
        batch = int(self._cfg.get("batch_size", 8))
        batch = data_axis * max(1, -(-batch // data_axis))
        step, params, opt_state = train_step(
            mesh, self._apply, self._params,
            optimizer=_make_optimizer(self._cfg))
        return mesh, step, params, opt_state, batch

    def _train_loop(self) -> None:
        try:
            self._train_loop_inner()
        except BaseException as e:  # noqa: BLE001 - surfaced via push/status
            self.error = e
            self.finished.set()
            if self.notify is not None:
                self.notify(EVENT_TRAINING_COMPLETION,
                            {"error": repr(e), **self.get_status()})

    def _train_loop_inner(self) -> None:
        import jax

        p = self.props
        per_epoch = int(p.num_training_samples)
        per_val = int(p.num_validation_samples)
        epochs = int(p.num_epochs)
        built = None
        xs, ys = [], []
        val_xs, val_ys = [], []
        epoch, seen_train, seen_val = 0, 0, 0
        losses: List[float] = []

        last_train: List = []  # last train batch, for sampled train acc

        def ensure_built(bx, by):
            nonlocal built
            if built is None:
                built = self._mesh_and_step(bx[0], by[0])
                self._sample_shape = (np.shape(bx[0]),
                                      np.asarray(bx[0]).dtype)
            return built

        def run_train(bx, by) -> float:
            nonlocal built
            mesh, step, params, opt_state, batch = ensure_built(bx, by)
            # pad by repetition to the static batch size (XLA needs a
            # fixed shape; dropping the tail would starve small datasets)
            while len(bx) < batch:
                bx = bx + bx[:batch - len(bx)]
                by = by + by[:batch - len(by)]
            x, y = np.stack(bx[:batch]), np.stack(by[:batch])
            params, opt_state, loss = step(params, opt_state, x, y)
            built = (mesh, step, params, opt_state, batch)
            self._params = params
            last_train[:] = [bx[:batch], by[:batch]]
            return float(loss)

        def run_eval(bxs, bys):
            """Loss/accuracy over the WHOLE given set, evaluated in
            batch-size chunks with the tail weighted by its true count
            (no truncation, no double-counted padding)."""
            from ..parallel.sharded import softmax_xent

            _, _, params, _, batch = ensure_built(bxs, bys)
            total, loss_sum, correct = 0, 0.0, 0
            for off in range(0, len(bxs), batch):
                cx, cy = bxs[off:off + batch], bys[off:off + batch]
                n = len(cx)
                while len(cx) < batch:  # pad, then weight by n only
                    cx = cx + cx[:batch - len(cx)]
                    cy = cy + cy[:batch - len(cy)]
                x, y = np.stack(cx), np.stack(cy)
                logits = np.asarray(self._apply(params, x))
                pred = logits.argmax(axis=-1)
                loss_sum += float(softmax_xent(
                    jax.numpy.asarray(logits[:n]), y[:n])) * n
                correct += int((pred[:n] == y[:n]).sum())
                total += n
            if not total:
                return 0.0, 0.0
            return loss_sum / total, correct / total

        def finish_epoch():
            nonlocal losses, val_xs, val_ys, seen_train, seen_val
            vloss, vacc = 0.0, 0.0
            if val_xs:
                vloss, vacc = run_eval(val_xs, val_ys)
                val_xs, val_ys = [], []
            tacc = 0.0
            if last_train:
                _, tacc = run_eval(last_train[0], last_train[1])
            with self._status_lock:
                self._status.update(
                    epoch=float(epoch),
                    training_loss=(sum(losses) / len(losses)) if losses
                    else 0.0,
                    training_accuracy=tacc,  # sampled on last train batch
                    validation_loss=vloss, validation_accuracy=vacc)
            losses.clear()
            seen_train, seen_val = 0, 0
            if self.notify is not None:
                self.notify(EVENT_EPOCH_COMPLETION, self.get_status())

        while not self._stop_evt.is_set():
            try:
                x, y, is_val = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if is_val or (per_val and seen_train >= per_epoch > 0):
                val_xs.append(x)
                val_ys.append(y)
                seen_val += 1
            else:
                xs.append(x)
                ys.append(y)
                seen_train += 1
                batch = int(self._cfg.get("batch_size", 8))
                epoch_done = per_epoch and seen_train >= per_epoch
                if len(xs) >= batch or (epoch_done and xs):
                    losses.append(run_train(xs, ys))
                    xs, ys = [], []
            if per_epoch and seen_train >= per_epoch and \
                    seen_val >= per_val:
                if xs:
                    losses.append(run_train(xs, ys))
                    xs, ys = [], []
                epoch += 1
                finish_epoch()
                if epochs and epoch >= epochs:
                    break
        if self.props.model_save_path:
            # save() raises a descriptive TrainerError when 'apply' is not
            # an import path — never silently discard trained params
            self.save(self.props.model_save_path)
        self.finished.set()
        if self.notify is not None and self.error is None:
            self.notify(EVENT_TRAINING_COMPLETION, self.get_status())

"""Pipeline container: graph assembly, negotiation, state, bus.

Replaces GstPipeline/GstBus for this framework.  ``Pipeline.start()`` runs
the static negotiation pass (sources outward, parity with the PAUSED-state
caps negotiation described at
/root/reference/gst/nnstreamer/tensor_filter/tensor_filter.c:188-194), then
spawns source threads.  ``bus`` carries ERROR/EOS/LATENCY/ELEMENT messages.
"""

from __future__ import annotations

import queue as _q
import threading
import time
from typing import Dict, List, Optional, Union

from ..obs import metrics as _metrics
from .element import Element, NegotiationError, Pad, SourceElement
from .events import Message, MessageKind


class Bus:
    """Message bus (parity: GstBus).  Watch handlers run synchronously in
    the posting thread, so handler registration is copy-on-write under a
    lock: ``post`` reads an immutable snapshot and never holds the lock
    while invoking handlers (a handler may itself add/remove watches)."""

    def __init__(self):
        self._q: "_q.Queue[Message]" = _q.Queue()
        self._handlers: tuple = ()
        self._handlers_lock = threading.Lock()

    def post(self, msg: Message) -> None:
        handlers = self._handlers  # immutable snapshot; no lock on post
        for h in handlers:
            h(msg)
        self._q.put(msg)

    def pop(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            return self._q.get(timeout=timeout)
        except _q.Empty:
            return None

    def add_watch(self, handler) -> None:
        with self._handlers_lock:
            self._handlers = self._handlers + (handler,)

    def remove_watch(self, handler) -> bool:
        """Remove ONE registration of a previously added watch (parity:
        gst_bus_remove_watch — paired add/remove by independent callers
        stays balanced).  Returns whether it was registered.  A ``post``
        racing with the removal may still deliver one last message to the
        handler."""
        with self._handlers_lock:
            # equality, not identity: bound methods compare equal across
            # distinct access objects (bus.remove_watch(self._watch))
            for i, h in enumerate(self._handlers):
                if h == handler:
                    self._handlers = (self._handlers[:i]
                                      + self._handlers[i + 1:])
                    return True
            return False


class Pipeline:
    def __init__(self, name: str = "pipeline", fuse: bool = True):
        self.name = name
        # transform↔filter fusion pass (SURVEY §7 stage 4); opt out with
        # fuse=False to run every element as its own computation
        self.fuse = fuse
        # captured single-dispatch segments (runtime/fusion.py
        # FusedSegment), rebuilt on every start()
        self.fused_segments: list = []
        self.elements: Dict[str, Element] = {}
        self.bus = Bus()
        self.playing = False
        self._eos_evt = threading.Event()
        self._err_evt = threading.Event()
        # single combined wake-up for wait_eos: set on EITHER terminal
        # condition so the waiter blocks on one event instead of
        # busy-polling two
        self._done_evt = threading.Event()
        self._first_error: Optional[Message] = None
        self._n_sinks = 0
        self._eos_sinks: set = set()
        self.bus.add_watch(self._watch)

    # -- assembly ------------------------------------------------------------

    def add(self, *elements: Element) -> "Pipeline":
        for e in elements:
            if e.name in self.elements:
                raise ValueError(f"duplicate element name {e.name!r}")
            self.elements[e.name] = e
            e.pipeline = self
        return self

    def __getitem__(self, name: str) -> Element:
        return self.elements[name]

    def link(self, *chain: Union[Element, str]) -> "Pipeline":
        """Link elements in sequence using their default src/sink pads."""
        els = [self.elements[c] if isinstance(c, str) else c for c in chain]
        for a, b in zip(els, els[1:]):
            self.link_pads(a, "src", b, "sink")
        return self

    def link_pads(self, a: Union[Element, str], apad: str,
                  b: Union[Element, str], bpad: str) -> "Pipeline":
        """Link ``a.apad`` → ``b.bpad``.  Re-linking an already-connected
        pad raises ``ValueError`` naming the existing peer — a link is
        never silently overwritten (unlink first to re-route)."""
        a = self.elements[a] if isinstance(a, str) else a
        b = self.elements[b] if isinstance(b, str) else b
        a.get_pad(apad).link(b.get_pad(bpad))
        return self

    # -- state ---------------------------------------------------------------

    def start(self) -> "Pipeline":
        if self.playing:
            return self
        # fresh terminal state for this run: a restarted pipeline must
        # not report the previous run's EOS/error from wait_eos()
        self._eos_evt.clear()
        self._err_evt.clear()
        self._done_evt.clear()
        self._eos_sinks.clear()
        self._first_error = None
        sources = [e for e in self.elements.values()
                   if isinstance(e, SourceElement)]
        if not sources:
            raise NegotiationError("pipeline has no source element")
        try:
            self._check_links()
            from .fusion import fuse_pipeline

            # whole-graph capture: collapse every eligible linear
            # transform→filter→decoder segment into one XLA program and
            # record the FusedSegment descriptors (digests key the
            # persistent compile cache; names label dispatch counting)
            fuse_pipeline(self, enable=self.fuse)
            # Negotiation: sources fix their caps and propagate downstream.
            for s in sources:
                s.negotiate()
            self._check_negotiated()
            self._n_sinks = sum(
                1 for e in self.elements.values()
                if not e.srcpads and e.sinkpads)
            # Start sinks/others before sources so data finds everything
            # live.
            for e in self.elements.values():
                if not isinstance(e, SourceElement):
                    e.start()
            for s in sources:
                s.start()
        except Exception:
            # A failed transition must not leak what already opened:
            # filters acquired during negotiation hold process-global
            # resources (serving-pool refcounts pin params in HBM), and
            # some elements may have started threads.  Roll back to NULL
            # — stop() is safe on never-started elements — then re-raise
            # the original failure.
            self.stop()
            raise
        self.playing = True
        # observability: the pipeline becomes visible to the process
        # metrics registry (weakly referenced — scrape-time pull only,
        # the hot path pays nothing; Documentation/observability.md)
        _metrics.REGISTRY.register_pipeline(self)
        # chaos: NNS_TPU_CHAOS installs a process-wide fault plan on
        # first pipeline start (Documentation/robustness.md)
        from ..chaos import hooks as _chaos_hooks

        _chaos_hooks.maybe_install_from_env()
        # flight recorder: NNS_TPU_FLIGHTREC_DIR arms dump-to-disk on
        # first pipeline start (Documentation/observability.md)
        from ..obs import flightrec as _flightrec

        _flightrec.maybe_arm_from_env()
        # watchdog: NNS_TPU_WATCH starts the alerting sampler on first
        # pipeline start (Documentation/observability.md, "Alerting")
        from ..obs import watch as _watch

        _watch.maybe_start_from_env()
        # host profiler: NNS_TPU_PROF starts the sampling profiler,
        # NNS_TPU_PROF_DEEP_DIR arms alert-triggered deep captures
        # (Documentation/observability.md, "Host execution profiling")
        from ..obs import prof as _prof

        _prof.maybe_start_from_env()
        # controller: NNS_TPU_CTL closes the loop — alerts steer the
        # actuator API (Documentation/observability.md, "Closed-loop
        # control & MTTR")
        from ..obs import control as _control

        _control.maybe_start_from_env()
        return self

    def stop(self) -> "Pipeline":
        _metrics.REGISTRY.unregister_pipeline(self)
        for e in self.elements.values():
            if isinstance(e, SourceElement):
                e.stop()
        for e in self.elements.values():
            if not isinstance(e, SourceElement):
                e.stop()
        # Going to NULL clears negotiated caps (GStreamer semantics): an
        # element relinked into another pipeline — or this pipeline
        # restarted — renegotiates from scratch instead of tripping over
        # stale pad schemas.
        for e in self.elements.values():
            for p in e.sinkpads + e.srcpads:
                p.caps = None
                p.spec = None
            e._eos_seen.clear()
        self.playing = False
        return self

    def _check_links(self) -> None:
        for e in self.elements.values():
            for p in e.sinkpads:
                if p.peer is None:
                    raise NegotiationError(
                        f"{e.name}.{p.name}: sink pad not linked")

    def _check_negotiated(self) -> None:
        for e in self.elements.values():
            for p in e.sinkpads + e.srcpads:
                if p.peer is not None and p.caps is None:
                    raise NegotiationError(
                        f"{e.name}.{p.name}: caps not negotiated "
                        f"(negotiation did not reach this pad)")

    def to_dot(self) -> str:
        """Graphviz dot of the pipeline graph with negotiated caps on the
        edges (parity: GST_DEBUG_DUMP_DOT_DIR pipeline dumps,
        /root/reference/tools/debugging/README.md)."""
        lines = [f'digraph "{self.name}" {{', "  rankdir=LR;",
                 "  node [shape=box, fontsize=10];"]
        for e in self.elements.values():
            label = f"{e.name}\\n({e.FACTORY if hasattr(e, 'FACTORY') else type(e).__name__})"
            lines.append(f'  "{e.name}" [label="{label}"];')
        seen = set()
        for e in self.elements.values():
            for sp in e.srcpads:
                if sp.peer is None:
                    continue
                key = (e.name, sp.name)
                if key in seen:
                    continue
                seen.add(key)
                caps = str(sp.caps) if sp.caps is not None else "?"
                caps = caps.replace('"', "'")
                lines.append(
                    f'  "{e.name}" -> "{sp.peer.element.name}" '
                    f'[label="{caps}", fontsize=8];')
        lines.append("}")
        return "\n".join(lines)

    # -- bus convenience ------------------------------------------------------

    def post(self, msg: Message) -> None:
        self.bus.post(msg)

    def _watch(self, msg: Message) -> None:
        if msg.kind == MessageKind.ERROR:
            if self._first_error is None:
                self._first_error = msg
            self._err_evt.set()
            self._done_evt.set()
        elif msg.kind == MessageKind.EOS:
            self._eos_sinks.add(msg.source)
            if len(self._eos_sinks) >= max(self._n_sinks, 1):
                self._eos_evt.set()
                self._done_evt.set()

    def wait_eos(self, timeout: Optional[float] = None,
                 raise_on_error: bool = True) -> bool:
        """Block until every sink reported EOS (or an error).  Waits on
        ONE combined event — an idle pipeline burns no CPU re-waking a
        poll loop (with no timeout the wait is a plain blocking wait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._err_evt.is_set():
                if raise_on_error:
                    raise RuntimeError(
                        f"pipeline error: {self._first_error}")
                return False
            if self._eos_evt.is_set():
                return True
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return False
            self._done_evt.wait(remain)

    @property
    def error(self) -> Optional[Message]:
        return self._first_error

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

"""The actuator API — named, bounded, reversible runtime knobs.

Five PRs of observability made the serving runtime *measurable*; this
module makes it *steerable* without making it *wreckable*.  Every
steerable object (a serving :class:`~nnstreamer_tpu.runtime.serving.
PoolEntry`'s cross-stream window, its admission controller, an edge
link's :class:`~nnstreamer_tpu.chaos.retrypolicy.RetryPolicy` breaker)
exposes a small set of :class:`Actuator` s — each one a **named**
operation with a **guard**:

- **bounded** — numeric requests clamp to ``[lo, hi]`` (the clamp is
  reported, never silent), so an external controller can nudge a batch
  window but can never set a 0-frame batch or a 10-minute deadline;
- **cooldown** — a minimum interval between actuations of the same
  knob (:class:`CooldownActive` rejection, counted by the caller), so
  an oscillating rule cannot saw a knob at sampler frequency;
- **reversible** — the first actuation snapshots the prior
  configuration; :meth:`Actuator.revert` restores it *exactly* (not
  just "a similar value": per-stream maps restore per stream).

Actuators read and write their target **through the owning entry**, not
through a captured object: a pool whose batcher was torn down by
``Pipeline.stop()`` raises a clean :class:`ActuationError` from the
racing actuation instead of poking a dead window — the same contract
as the registry's scrape-vs-stop tolerance.

Discovery: :func:`list_actuators` walks the process-wide steerable
objects (``MODEL_POOL`` entries, registered ``RetryPolicy`` links) at
call time — like the metrics registry, nothing is pushed; targets
appear and disappear with the objects that own them.  The controller
(``obs/control.py``) and ``nns-ctl`` both resolve targets through this
one function.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: default minimum seconds between actuations of one knob
DEFAULT_COOLDOWN_S = 0.5

#: actuator names by target kind — the static catalog ``nns-lint``
#: NNS511 validates controller playbooks against (a playbook naming an
#: actuator nothing exports can never act; that is a config bug worth a
#: warning, not a 3am surprise)
KNOWN_ACTUATORS: Dict[str, Tuple[str, ...]] = {
    "pool": ("window-ms", "max-batch", "coalescing", "ramp-start",
             "queue-limit"),
    "link": ("breaker",),
    # model lifecycle (runtime/lifecycle.py): swap/canary take the
    # model reference as a TEXT value; promote/rollback are numeric
    # (playbook-drivable verdict knobs)
    "model": ("swap", "canary", "promote", "rollback"),
}


class ActuationError(ValueError):
    """An actuation could not apply (target gone, guard violated)."""


class CooldownActive(ActuationError):
    """Rejected: the knob was actuated more recently than its
    cooldown allows."""


class Actuator:
    """One named, bounded, reversible knob on one target.

    ``get_fn``/``set_fn`` read/write the live value (raising
    :class:`ActuationError` when the underlying object is gone);
    ``snapshot_fn``/``restore_fn`` optionally override how the prior
    configuration is captured and restored when a scalar is not enough
    (e.g. per-stream queue limits restore per stream).
    """

    def __init__(self, name: str, kind: str, target: str,
                 get_fn: Callable[[], Any],
                 set_fn: Callable[[float], None],
                 lo: Optional[float] = None, hi: Optional[float] = None,
                 unit: str = "", cooldown_s: float = DEFAULT_COOLDOWN_S,
                 snapshot_fn: Optional[Callable[[], Any]] = None,
                 restore_fn: Optional[Callable[[Any], None]] = None,
                 text: bool = False):
        self.name = name
        self.kind = kind
        self.target = target
        self.unit = unit
        #: text=True: the knob consumes a STRING value (a model
        #: reference on the lifecycle's swap/canary knobs) — no
        #: clamping; numeric values still pass through for knobs that
        #: accept both (canary N)
        self.text = bool(text)
        self.lo = lo
        self.hi = hi
        self.cooldown_s = float(cooldown_s)
        self._get = get_fn
        self._set = set_fn
        self._snapshot = snapshot_fn or get_fn
        self._restore = restore_fn or (lambda prior: set_fn(prior))
        self._lock = threading.Lock()
        self._last_ts: Optional[float] = None
        #: prior config captured at the FIRST deviation, consumed by
        #: revert() — "reversible" means the exact pre-steering state
        self._initial: Any = None
        self._dirty = False

    # -- introspection --------------------------------------------------------

    def read(self) -> Any:
        """Current value (None when the target is gone)."""
        try:
            return self._get()
        except ActuationError:
            return None

    def describe(self) -> dict:
        with self._lock:
            dirty = self._dirty
        return {"kind": self.kind, "target": self.target,
                "actuator": self.name, "value": self.read(),
                "lo": self.lo, "hi": self.hi, "unit": self.unit,
                "cooldown_s": self.cooldown_s, "dirty": dirty}

    def clamp(self, value: float) -> float:
        if self.text and isinstance(value, str):
            return value  # text knobs take references, not numbers
        v = float(value)
        if self.lo is not None:
            v = max(v, self.lo)
        if self.hi is not None:
            v = min(v, self.hi)
        return v

    # -- the guarded write ----------------------------------------------------

    def actuate(self, value: float,
                now: Optional[float] = None) -> dict:
        """Apply ``value`` (clamped, cooldown-guarded).  Returns the
        actuation record; raises :class:`CooldownActive` on a too-soon
        repeat and :class:`ActuationError` when the target is gone."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if self._last_ts is not None \
                    and now - self._last_ts < self.cooldown_s:
                raise CooldownActive(
                    f"{self.target}.{self.name}: cooldown "
                    f"({self.cooldown_s:g}s) active — "
                    f"{now - self._last_ts:.2f}s since last actuation")
            prior = self._get()
            applied = self.clamp(value)
            if not self._dirty:
                self._initial = self._snapshot()
                self._dirty = True
            self._set(applied)
            self._last_ts = now
            requested = value if (self.text and isinstance(value, str)) \
                else float(value)
            return {"kind": self.kind, "target": self.target,
                    "actuator": self.name,
                    "requested": requested, "applied": applied,
                    "prior": prior,
                    "clamped": applied != requested}

    def revert(self, now: Optional[float] = None) -> Optional[dict]:
        """Restore the exact pre-steering configuration (None when
        nothing was ever applied).  Bypasses the cooldown — backing out
        is always allowed — but stamps it, so the next forward
        actuation still waits."""
        with self._lock:
            if not self._dirty:
                return None
            now = time.monotonic() if now is None else now
            prior = self._get()
            initial = self._initial
            self._restore(initial)
            self._dirty = False
            self._initial = None
            self._last_ts = now
            return {"kind": self.kind, "target": self.target,
                    "actuator": self.name, "requested": None,
                    "applied": initial, "prior": prior,
                    "clamped": False, "reverted": True}


# -- discovery ----------------------------------------------------------------


def _pool_sets() -> List[Tuple[str, Dict[str, Actuator]]]:
    from .serving import MODEL_POOL

    out = []
    with MODEL_POOL._lock:
        entries = list(MODEL_POOL._entries.values())
    for entry in entries:
        out.append((entry.label(), entry.actuators()))
    return out


def _link_sets() -> List[Tuple[str, Dict[str, Actuator]]]:
    from ..chaos.retrypolicy import RetryPolicy

    return [(pol.name or "link", pol.actuators())
            for pol in RetryPolicy.all_policies()]


def _model_sets() -> List[Tuple[str, Dict[str, Actuator]]]:
    """Model-lifecycle knobs (runtime/lifecycle.py): one set per live
    pool entry — swapping/canarying is a pool-level operation, so the
    targets mirror the pool actuators' labels."""
    from .serving import MODEL_POOL

    out = []
    with MODEL_POOL._lock:
        entries = list(MODEL_POOL._entries.values())
    for entry in entries:
        out.append((entry.label(), entry.lifecycle.actuators()))
    return out


def list_actuators(kind: Optional[str] = None) -> List[Actuator]:
    """Every live actuator in the process, pools first (stable order
    within a scrape; targets come and go with their owners)."""
    out: List[Actuator] = []
    if kind in (None, "pool"):
        for _label, acts in _pool_sets():
            out.extend(acts.values())
    if kind in (None, "model"):
        for _label, acts in _model_sets():
            out.extend(acts.values())
    if kind in (None, "link"):
        for _label, acts in _link_sets():
            out.extend(acts.values())
    return out


def find_actuators(kind: str, target: str,
                   name: str) -> List[Actuator]:
    """Actuators matching ``(kind, target-glob, name)`` — possibly
    several (two links may share a name), possibly none (the caller
    reports ``no-target``, it is not an exception)."""
    import fnmatch

    out = []
    for act in list_actuators(kind):
        if act.name != name:
            continue
        if target and target != "*" \
                and not fnmatch.fnmatch(act.target, target):
            continue
        out.append(act)
    return out

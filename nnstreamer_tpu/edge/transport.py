"""Edge transports: in-process (zero-copy) and TCP (wire-serialized).

Parity target: the nnstreamer-edge communication library the reference's
L5 layer consumes (``nns_edge_create_handle/start/send/event_cb``,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:541-557,
gst/edge/edge_sink.c:291-334; connect types TCP/HYBRID/MQTT/AITT).

TPU-native redesign: two connect types.

- ``inproc`` — client and server pipelines share the process: envelopes
  carry :class:`~nnstreamer_tpu.core.Buffer` objects *by reference*, so
  device-resident tensors never leave HBM and offloading a stage costs a
  queue hop, not a serialize/deserialize round-trip.  This is the default
  for same-host stage offload (SURVEY.md §7.6).
- ``tcp`` — cross-host: envelopes serialize through
  :mod:`nnstreamer_tpu.edge.wire` (MetaInfo-headed payloads) over a
  length-prefixed socket stream.  The same element graph works unchanged.

Both present the same two interfaces: :class:`ServerTransport`
(accept + per-client send + topic publish) and :class:`ClientConn`
(send + blocking receive + caps query).
"""

from __future__ import annotations

import dataclasses
import queue
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from ..core import Buffer
from ..utils.log import logd, logw
from .wire import (
    EdgeMessage,
    MSG_CAPS_REQ,
    MSG_CAPS_RES,
    MSG_PUBLISH,
    MSG_QUERY,
    MSG_REPLY,
    MSG_SUBSCRIBE,
)


@dataclasses.dataclass
class Envelope:
    """Transport-neutral message: what the elements see.  ``buffer`` is
    by-reference for inproc and (de)serialized at the socket boundary for
    tcp."""

    mtype: int
    client_id: int = 0
    seq: int = 0
    info: str = ""
    buffer: Optional[Buffer] = None


def _to_wire(env: Envelope) -> bytes:
    if env.buffer is not None:
        msg = EdgeMessage.from_buffer(env.mtype, env.buffer,
                                      client_id=env.client_id, seq=env.seq,
                                      info=env.info)
    else:
        msg = EdgeMessage(mtype=env.mtype, client_id=env.client_id,
                          seq=env.seq, info=env.info)
    return msg.pack()


def _from_wire(data: bytes) -> Envelope:
    msg = EdgeMessage.unpack(data)
    buf = msg.to_buffer() if msg.payloads else None
    return Envelope(mtype=msg.mtype, client_id=msg.client_id, seq=msg.seq,
                    info=msg.info, buffer=buf)


# -- server side --------------------------------------------------------------


class ServerTransport:
    """Interface: accept clients, deliver inbound envelopes to
    ``on_message(client_id, env)``, send/publish outbound ones."""

    def __init__(self):
        self.on_message: Optional[Callable[[int, Envelope], None]] = None
        self.caps_provider: Optional[Callable[[], str]] = None

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def send(self, client_id: int, env: Envelope) -> bool:
        raise NotImplementedError

    def publish(self, env: Envelope) -> int:
        """Send to every subscriber whose topic matches ``env.info``
        (empty subscription = all topics).  Returns receiver count."""
        raise NotImplementedError

    # shared control-message handling
    def _dispatch(self, client_id: int, env: Envelope,
                  subscribe_cb: Callable[[int, str], None]) -> None:
        if env.mtype == MSG_CAPS_REQ:
            caps = self.caps_provider() if self.caps_provider else ""
            self.send(client_id, Envelope(
                MSG_CAPS_RES, client_id=client_id, seq=env.seq, info=caps))
        elif env.mtype == MSG_SUBSCRIBE:
            subscribe_cb(client_id, env.info)
        elif self.on_message is not None:
            self.on_message(client_id, env)


class ClientConn:
    """Interface: one client connection."""

    def send(self, env: Envelope) -> bool:
        raise NotImplementedError

    def is_alive(self) -> bool:
        """False once the peer is gone — lets a pipelined caller
        distinguish "no data yet" from "connection dead" after a
        timed-out recv (mid-stream failover)."""
        return True

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        raise NotImplementedError

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# -- inproc -------------------------------------------------------------------

_HUB_LOCK = threading.Lock()
_HUB: Dict[Tuple[str, int], "InprocServer"] = {}


class InprocServer(ServerTransport):
    """Zero-copy in-process transport: a global hub maps (host, port) to
    the server; envelopes cross as Python references."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.addr = (host, int(port))
        self._clients: Dict[int, "InprocClientConn"] = {}
        self._subs: Dict[int, str] = {}  # client_id → topic
        self._next_id = 1
        self._lock = threading.Lock()

    def start(self) -> None:
        with _HUB_LOCK:
            if self.addr in _HUB:
                raise OSError(f"inproc address already bound: {self.addr}")
            _HUB[self.addr] = self

    def stop(self) -> None:
        with _HUB_LOCK:
            if _HUB.get(self.addr) is self:
                del _HUB[self.addr]
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._subs.clear()
        for c in clients:
            c._closed.set()

    def _connect(self, conn: "InprocClientConn") -> int:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            self._clients[cid] = conn
        return cid

    def _disconnect(self, client_id: int) -> None:
        with self._lock:
            self._clients.pop(client_id, None)
            self._subs.pop(client_id, None)

    def _receive(self, client_id: int, env: Envelope) -> None:
        env.client_id = client_id
        self._dispatch(client_id, env, self._subscribe)

    def _subscribe(self, client_id: int, topic: str) -> None:
        with self._lock:
            self._subs[client_id] = topic

    def send(self, client_id: int, env: Envelope) -> bool:
        with self._lock:
            conn = self._clients.get(client_id)
        if conn is None:
            return False
        conn._deliver(env)
        return True

    def publish(self, env: Envelope) -> int:
        with self._lock:
            targets = [cid for cid, topic in self._subs.items()
                       if not topic or topic == env.info]
        return sum(bool(self.send(cid, env)) for cid in targets)


class InprocClientConn(ClientConn):
    def __init__(self, host: str, port: int):
        with _HUB_LOCK:
            server = _HUB.get((host, int(port)))
        if server is None:
            raise ConnectionRefusedError(
                f"no inproc server at {host}:{port}")
        self._server = server
        self._inbox: "queue.Queue[Envelope]" = queue.Queue()
        self._caps: "queue.Queue[str]" = queue.Queue()
        self._closed = threading.Event()
        self.client_id = server._connect(self)

    def _deliver(self, env: Envelope) -> None:
        # route control responses to their own queue so a caps handshake
        # never races with data replies
        if env.mtype == MSG_CAPS_RES:
            self._caps.put(env.info)
        else:
            self._inbox.put(env)

    def send(self, env: Envelope) -> bool:
        if self._closed.is_set():
            return False
        self._server._receive(self.client_id, env)
        return True

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            return None

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        self.send(Envelope(MSG_CAPS_REQ))
        try:
            return self._caps.get(timeout=timeout)
        except queue.Empty:
            return None

    def is_alive(self) -> bool:
        return not self._closed.is_set()

    def close(self) -> None:
        self._closed.set()
        self._server._disconnect(self.client_id)


# -- tcp ----------------------------------------------------------------------


def _send_frame(sock: socket.socket, data: bytes, lock: threading.Lock
                ) -> bool:
    try:
        with lock:
            sock.sendall(struct.pack("<I", len(data)) + data)
        return True
    except OSError:
        return False


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    try:
        hdr = _recv_exact(sock, 4)
        if hdr is None:
            return None
        (n,) = struct.unpack("<I", hdr)
        return _recv_exact(sock, n)
    except OSError:
        return None


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        c = sock.recv(n)
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


class TcpServer(ServerTransport):
    """Socket server: one reader thread per client connection."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.host, self.port = host, int(port)
        self._sock: Optional[socket.socket] = None
        self._conns: Dict[int, Tuple[socket.socket, threading.Lock]] = {}
        self._subs: Dict[int, str] = {}
        self._next_id = 1
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        if self.port == 0:
            self.port = s.getsockname()[1]
        s.listen(16)
        self._sock = s
        self._running.set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"edge-accept:{self.port}",
            daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            self._subs.clear()
        for sock, _ in conns:
            try:
                sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._conns[cid] = (conn, threading.Lock())
            logd("edge: client %d connected from %s", cid, addr)
            threading.Thread(target=self._reader, args=(cid, conn),
                             name=f"edge-read:{cid}", daemon=True).start()

    def _reader(self, cid: int, conn: socket.socket) -> None:
        while self._running.is_set():
            data = _recv_frame(conn)
            if data is None:
                break
            try:
                env = _from_wire(data)
            except ValueError as e:
                logw("edge: dropping bad frame from client %d: %s", cid, e)
                continue
            env.client_id = cid
            self._dispatch(cid, env, self._subscribe)
        with self._lock:
            self._conns.pop(cid, None)
            self._subs.pop(cid, None)
        try:
            conn.close()
        except OSError:
            pass

    def _subscribe(self, client_id: int, topic: str) -> None:
        with self._lock:
            self._subs[client_id] = topic

    def send(self, client_id: int, env: Envelope) -> bool:
        with self._lock:
            entry = self._conns.get(client_id)
        if entry is None:
            return False
        return _send_frame(entry[0], _to_wire(env), entry[1])

    def publish(self, env: Envelope) -> int:
        with self._lock:
            targets = [cid for cid, topic in self._subs.items()
                       if not topic or topic == env.info]
        return sum(bool(self.send(cid, env)) for cid in targets)


class TcpClientConn(ClientConn):
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._inbox: "queue.Queue[Envelope]" = queue.Queue()
        self._caps: "queue.Queue[str]" = queue.Queue()
        self._closed = threading.Event()
        self._dead = threading.Event()
        self._reader_thread = threading.Thread(
            target=self._reader, name="edge-client-read", daemon=True)
        self._reader_thread.start()

    def _reader(self) -> None:
        while not self._closed.is_set():
            data = _recv_frame(self._sock)
            if data is None:
                break
            try:
                env = _from_wire(data)
            except ValueError as e:
                logw("edge: client dropping bad frame: %s", e)
                continue
            if env.mtype == MSG_CAPS_RES:
                self._caps.put(env.info)
            else:
                self._inbox.put(env)
        self._dead.set()

    def send(self, env: Envelope) -> bool:
        if self._closed.is_set():
            return False
        return _send_frame(self._sock, _to_wire(env), self._wlock)

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            return None

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        if not self.send(Envelope(MSG_CAPS_REQ)):
            return None
        try:
            return self._caps.get(timeout=timeout)
        except queue.Empty:
            return None

    def is_alive(self) -> bool:
        return not self._closed.is_set() and not self._dead.is_set()

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# -- factories ----------------------------------------------------------------


def make_server(host: str, port: int, connect_type: str = "tcp"
                ) -> ServerTransport:
    if connect_type == "inproc":
        return InprocServer(host, port)
    if connect_type == "tcp":
        return TcpServer(host, port)
    raise ValueError(f"unknown connect-type {connect_type!r}")


def connect(host: str, port: int, connect_type: str = "tcp",
            timeout: float = 5.0) -> ClientConn:
    if connect_type == "inproc":
        return InprocClientConn(host, port)
    if connect_type == "tcp":
        return TcpClientConn(host, port, timeout=timeout)
    raise ValueError(f"unknown connect-type {connect_type!r}")

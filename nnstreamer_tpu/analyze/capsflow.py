"""Pass 2 — caps dry-run (NNS2xx, NNS108).

Propagates caps sources-outward through the whole assembled graph using
the *real* negotiation machinery (``SourceElement.negotiate`` →
``Element.set_caps``/``negotiate_src_pads`` and every element override),
exactly as ``Pipeline.start()`` would in its PAUSED-equivalent pass — but
as a pure function: no fusion rewrite, no element ``start()``, no
threads, and every pad's caps/spec state is restored afterwards.

Failures are *collected*, not raised, and classified via the structured
context on :class:`NegotiationError` (reason / pads / caps on each side),
so a finding names the exact link and — for empty intersections — the
exact caps field that killed the negotiation (rank-flexible ``dimensions``
compare and ``framerate`` 0/1 wildcards included, parity:
``gst_tensor_caps_can_intersect``).
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional, Tuple

from ..core.caps import Caps, _intersect_value
from ..runtime.element import Element, NegotiationError, SourceElement
from ..runtime.pipeline import Pipeline
from .diagnostics import Diagnostic


def caps_dry_run(pipe: Pipeline, fragment: bool = False) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    pads = [p for e in pipe.elements.values()
            for p in e.sinkpads + e.srcpads]
    saved = [(p, p.caps, p.spec) for p in pads]
    try:
        sources = [e for e in pipe.elements.values()
                   if isinstance(e, SourceElement)]
        for s in sources:
            if not s.srcpads or all(sp.peer is None for sp in s.srcpads):
                continue  # graph pass already reports the dangling pad
            try:
                s.negotiate()
            except NegotiationError as e:
                diags.append(_classify(e, s))
            except OSError as e:
                # source schema lives in a runtime file (datareposrc
                # json descriptor, sensor sysfs dir, ...) not present now
                diags.append(Diagnostic.make(
                    "NNS203", f"{s.name}: source schema depends on a "
                    f"file unavailable at analysis time: {e}",
                    element=s.name,
                    hint="the dry-run cannot follow this branch; the "
                         "file is read when the pipeline starts"))
            except (ValueError, TypeError, KeyError) as e:
                # an element override raised raw — still a negotiation
                # failure, just without structured context
                diags.append(Diagnostic.make(
                    "NNS204", f"{s.name}: negotiation failed: {e}",
                    element=s.name))
        if not diags and not fragment:
            # only when nothing else explains it: a pad negotiation never
            # reached with zero findings means a source-less island
            # (fragments have unreached pads by definition)
            diags += _unreached_pads(pipe)
        diags += _fan_in_rates(pipe)
    finally:
        for p, caps, spec in saved:
            p.caps, p.spec = caps, spec
    return diags


# -- classification ----------------------------------------------------------


def _link_name(e: NegotiationError, fallback: Element) -> Tuple[str, str]:
    """(element, pad) naming the failing spot."""
    if e.src_pad is not None and e.sink_pad is not None:
        return (e.src_pad.element.name,
                f"{e.src_pad.name} -> "
                f"{e.sink_pad.element.name}.{e.sink_pad.name}")
    for pad in (e.sink_pad, e.src_pad):
        if pad is not None:
            return pad.element.name, pad.name
    return fallback.name, ""


def _classify(e: NegotiationError, source: Element) -> Diagnostic:
    el, pad = _link_name(e, source)
    if e.reason == "no-spec":
        return Diagnostic.make(
            "NNS203", f"{e}", element=el, pad=pad or None,
            hint="the source's output schema is set programmatically "
                 "(spec=/caps=) before start; the dry-run cannot follow "
                 "this branch")
    if e.reason == "open":
        return Diagnostic.make(
            "NNS205", f"{e}", element=el, pad=pad or None,
            hint="the model/sub-plugin is resolved at runtime "
                 "(register_model, model files); caps cannot be verified "
                 "statically for this element")
    if e.reason == "empty":
        field = _explain_empty(e.upstream, e.downstream)
        msg = str(e)
        if field:
            msg += f" — first incompatible field: {field}"
        return Diagnostic.make(
            "NNS201", msg, element=el, pad=pad or None,
            hint="fix the named field on one side of the link (insert a "
                 "tensor_transform / tensor_converter, or relax the "
                 "capsfilter)")
    if e.reason == "unfixable":
        field = _unfixed_field(e.upstream)
        msg = str(e)
        if field:
            msg += f" — non-fixable field: {field}"
        return Diagnostic.make(
            "NNS202", msg, element=el, pad=pad or None,
            hint="constrain the field to a concrete value (capsfilter) so "
                 "fixation can pick one")
    return Diagnostic.make(
        "NNS204", f"{e}", element=el, pad=pad or None,
        hint="the element's negotiation hook rejected the incoming caps; "
             "see the message for the element's reason")


def _explain_empty(up: Optional[Caps], down: Optional[Caps]
                   ) -> Optional[str]:
    """Name the first field whose values cannot intersect (or the media
    type, when no struct pair shares a mimetype)."""
    if up is None or down is None or up.is_empty() or down.is_empty():
        return None
    mime_pair = False
    for a in up.structs:
        for b in down.structs:
            if a.mime != b.mime and "*" not in (a.mime, b.mime):
                continue
            mime_pair = True
            ad, bd = a.as_dict(), b.as_dict()
            for k in sorted(set(ad) & set(bd)):
                ok, _ = _intersect_value(k, ad[k], bd[k])
                if not ok:
                    return (f"{k} ({_fmt_value(ad[k])} vs "
                            f"{_fmt_value(bd[k])})")
    if not mime_pair:
        a = up.structs[0].mime
        b = down.structs[0].mime
        return f"media type ({a} vs {b})"
    return None


def _fmt_value(v) -> str:
    if isinstance(v, frozenset):
        return "{" + ",".join(sorted(str(x) for x in v)) + "}"
    return str(v)


def _unfixed_field(caps: Optional[Caps]) -> Optional[str]:
    from ..core.caps import _is_fixed_value

    if caps is None or caps.is_empty():
        return None
    s = caps.structs[0]
    if s.mime == "*":
        return "media type (wildcard)"
    for k, v in s.fields:
        if not _is_fixed_value(k, v):
            return f"{k} ({_fmt_value(v)})"
    return None


# -- post-propagation checks -------------------------------------------------


def _unreached_pads(pipe: Pipeline) -> List[Diagnostic]:
    """Linked pads negotiation never reached with no other caps finding —
    usually an island of linked elements with no source feeding it."""
    diags: List[Diagnostic] = []
    for e in pipe.elements.values():
        for p in e.sinkpads + e.srcpads:
            if p.peer is not None and p.caps is None:
                diags.append(Diagnostic.make(
                    "NNS206",
                    f"negotiation did not reach {e.name}.{p.name}",
                    element=e.name, pad=p.name,
                    hint="caused by an upstream finding, or an upstream "
                         "branch whose caps are only known at runtime"))
    return diags


def _fan_in_rates(pipe: Pipeline) -> List[Diagnostic]:
    """NNS108: fan-in elements (mux/merge/aggregator/crop — anything with
    several linked sink pads) whose negotiated input framerates disagree.
    ``0/1`` is the reference's "any rate" wildcard and matches anything."""
    diags: List[Diagnostic] = []
    for e in pipe.elements.values():
        linked = [p for p in e.sinkpads if p.peer is not None]
        if len(linked) < 2:
            continue
        rates = {}
        for p in linked:
            rate = None
            if p.spec is not None:
                rate = p.spec.rate
            elif p.caps is not None and not p.caps.is_empty():
                rate = p.caps.structs[0].get("framerate")
            if rate in (None, ""):
                continue
            rate = Fraction(rate)
            if rate != 0:
                rates[p.name] = rate
        if len(set(rates.values())) > 1:
            detail = ", ".join(f"{k}={v}" for k, v in sorted(rates.items()))
            diags.append(Diagnostic.make(
                "NNS108",
                f"{e.name}: fan-in inputs disagree on framerate "
                f"({detail}) — sync policies will stall or drop",
                element=e.name,
                hint="rate-match the branches (tensor_rate) or use "
                     "sync_mode=nosync/refresh deliberately"))
    return diags

"""``tensor_filter`` — the NN invoke element, and the single-shot invoker.

Parity targets:
- element + dispatch core: /root/reference/gst/nnstreamer/tensor_filter/
  tensor_filter.c (transform hot path :643-880, throttling :511, stats
  :366-468) and tensor_filter_common.c (open_fw :2465, framework
  auto-detection :1224, input/output-combination parsing).
- single-shot: tensor_filter_single.c (invoke without a pipeline).

TPU-native redesign of the hot path: tensors stay ``jax.Array``; ``invoke``
is an async XLA dispatch so the streaming thread pipelines ahead of the
device.  The reference's per-invoke output malloc+memcpy
(tensor_filter.c:760-809) has no equivalent — XLA allocates outputs in HBM
(allocate-in-invoke always on).
"""

from __future__ import annotations

import time
from contextlib import nullcontext as _nullcontext
from fractions import Fraction
from typing import Any, List, Optional, Sequence

from ..chaos import hooks as _chaos_hooks
from ..chaos.plan import apply_invoke_fault
from ..core import Buffer, Caps, Tensor, TensorFormat, TensorsSpec
from ..filters.api import FilterError, FilterProps, FilterSubplugin
from ..filters.registry import detect_framework, find_filter
from ..obs import hooks as _hooks
from ..obs import stagestat as _stagestat
from ..obs import transfer as _xfer
from ..obs.tracer import TRACE_META_KEY
from ..runtime.element import Element, NegotiationError, Pad, StreamError
from ..runtime.events import Event, EventKind, Message, MessageKind
from ..runtime.registry import register_element
from ..runtime.serving import block_all
from ..utils import profile as _profile
from ..utils.stats import InvokeStats


def _parse_combination(s: str) -> Optional[List[int]]:
    if not s:
        return None
    return [int(x) for x in str(s).split(",") if str(x).strip() != ""]


#: meta marker riding a frame that crossed a stage boundary (set at
#: the handoff ingress, consumed — and stripped — at the stage's emit
#: seams so the inter-stage depth decrements exactly once per frame)
_STAGE_META = "nns.stage.handoff"


def _device_ids_of(t: Tensor) -> tuple:
    """Device ids a device-resident tensor currently lives on (empty
    when the runtime can't say — treated as already-local)."""
    try:
        arr = t.jax()
        devs = arr.devices() if callable(getattr(arr, "devices", None)) \
            else {arr.device}
        return tuple(sorted(int(d.id) for d in devs))
    except Exception:  # noqa: BLE001 - telemetry-adjacent: never raise
        return ()


def _trace_ids(bufs: Sequence[Buffer]) -> List[str]:
    """Obs trace ids riding a dispatch's buffers (usually empty: only
    1-in-N sampled frames carry a trace)."""
    out = []
    for b in bufs:
        tr = b.meta.get(TRACE_META_KEY)
        if tr is not None and tr.get("id"):
            out.append(str(tr["id"]))
    return out


@register_element("tensor_filter")
class TensorFilter(Element):
    FACTORY = "tensor_filter"

    def __init__(self, name=None, framework: str = "auto", model: Any = None,
                 accelerator: str = "", custom: str = "",
                 input_combination: str = "", output_combination: str = "",
                 invoke_dynamic: bool = False, is_updatable: bool = False,
                 shared_tensor_filter_key: str = "", latency: int = 0,
                 latency_report: bool = False, inputtype: str = "",
                 input: str = "", outputtype: str = "", output: str = "",
                 mesh: str = "", sharding: str = "", devices: str = "",
                 batch: int = 1, batch_timeout_ms: float = 1.0,
                 batch_buckets: str = "", share_model: bool = False,
                 stat_sample_interval_ms: Optional[float] = None,
                 priority: str = "normal", deadline_ms: float = 0.0,
                 slo_ms: float = 0.0, queue_limit: int = 0,
                 canary: str = "", tenant: str = "", chaos: str = "",
                 **props):
        self.framework = framework
        self.model = model
        self.accelerator = accelerator
        self.custom = custom
        self.input_combination = input_combination
        self.output_combination = output_combination
        self.invoke_dynamic = invoke_dynamic
        self.is_updatable = is_updatable
        self.shared_tensor_filter_key = shared_tensor_filter_key
        self.latency = latency          # 1 = measure synchronously
        self.latency_report = latency_report
        self.inputtype, self.input = inputtype, input
        self.outputtype, self.output = outputtype, output
        # multi-chip: mesh="data:-1" compiles the invoke SPMD over a device
        # mesh (SURVEY.md §7.6 — the pjit answer to remote tensor_filter);
        # devices="0-3" restricts the mesh to a submesh so pipeline stages
        # can occupy disjoint device subsets
        self.mesh = mesh
        self.sharding = sharding
        self.devices = devices
        # dynamic micro-batching (runtime/batching.py): batch>1 coalesces
        # in-flight buffers into ONE XLA dispatch per window; buckets
        # bound the set of compiled shapes; timeout bounds added latency
        self.batch = batch
        self.batch_timeout_ms = batch_timeout_ms
        self.batch_buckets = batch_buckets
        # shared-model serving (runtime/serving.py): share-model=true
        # attaches this element to the process-wide ModelPool — N filters
        # on the same model share ONE sub-plugin instance (one params
        # copy, one executable cache) and, with batch>1, one CROSS-
        # pipeline coalescing window
        self.share_model = share_model
        # observability: cadence of the blocking latency sample —
        # None = the class default STAT_SAMPLE_INTERVAL (so tuning the
        # class attribute still works); shrink for a fresher `nns-top`
        # LAT column, grow to make sampling arbitrarily rare
        self.stat_sample_interval_ms = stat_sample_interval_ms
        # SLO-aware admission (runtime/admission.py, share-model only):
        # priority names this STREAM's class (high/normal/low),
        # deadline-ms its per-frame deadline (0 = the pool SLO),
        # queue-limit bounds its parked frames (0 = 16x batch);
        # slo-ms is POOL-level — >0 arms the admission controller,
        # which sheds sub-high-priority frames while the pool's p99
        # threatens the SLO (every shed counted + bus-warned)
        self.priority = priority
        self.deadline_ms = deadline_ms
        self.slo_ms = slo_ms
        self.queue_limit = queue_limit
        # tenant attribution (obs/tenantstat.py, share-model only):
        # tenant= names who this STREAM's frames are billed to — every
        # pool dispatch splits its device-seconds across tenants by
        # useful-frame occupancy (nns_tenant_* families, snapshot v9
        # tenants table); default tenant "default"
        self.tenant = tenant
        # model lifecycle (runtime/lifecycle.py, share-model only):
        # canary="<version>:1/N" (or "1/N") is POOL-level — a reload
        # routes 1-in-N of the pool's streams to the new version and
        # the watch/playbook pair judges promote-or-rollback, instead
        # of cutting every stream over at once
        self.canary = canary
        # version tag split off a versioned model reference
        # (filters/modeluri.py `model.pkl@v2`) — swap provenance
        self.model_version = ""
        # deterministic fault injection scoped to THIS element (the
        # process-wide NNS_TPU_CHAOS plan applies regardless); grammar
        # in chaos/plan.py, e.g. "seed=7;slow-invoke:ms=20,p=0.1"
        self.chaos = chaos
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self.subplugin: Optional[FilterSubplugin] = None
        self.in_spec: Optional[TensorsSpec] = None
        self.out_spec: Optional[TensorsSpec] = None
        self.invoke_stats = InvokeStats()
        self._in_combi = None
        self._out_combi = None
        self._throttle_interval = 0.0
        self._last_invoke_ts = 0.0
        self._dyn_spec: Optional[TensorsSpec] = None
        self._fused_pre: list = []  # op chains inlined by runtime/fusion.py
        self._fused_post: list = []  # epilogue fns (decoder overlay fusion)
        self._fused_post_decoder = None  # Decoder obj to notify on unfuse
        self._invoke_seq = 0
        self._last_sample_ts = 0.0
        self._last_out: Any = None  # previous invoke's output (drain point)
        self._batcher = None         # MicroBatcher when batch>1 (start())
        self._buckets: tuple = (1,)
        self._pool_entry = None      # serving.PoolEntry (share-model=true)
        self._pool_attached = False  # registered as a live pool stream
        self._pool_batched = False   # frames go through the SharedBatcher
        self._chaos_plan = None      # parsed from the chaos= prop (start)

    #: Sampled invokes block on the outputs so latency/throughput stats
    #: measure device *execution*, not async dispatch (XLA dispatch
    #: returns in ~µs regardless of the computation).  Sampling is
    #: TIME-based — at most one blocking sample per interval — because a
    #: block costs a full device round-trip, which on a remote/tunneled
    #: device is ~100 ms: a count-based every-Nth rule would burn a fixed
    #: fraction of throughput on stats.  Unsampled invokes run ahead of
    #: the device.  ``latency=1`` forces every invoke synchronous
    #: (reference prop).  Per element, the ``stat-sample-interval-ms``
    #: property overrides this class-wide default (seconds here, ms on
    #: the property).
    STAT_SAMPLE_INTERVAL = 1.0

    # -- open ----------------------------------------------------------------

    def _user_spec(self, dims: str, types: str) -> Optional[TensorsSpec]:
        if not dims or not types:
            return None
        return TensorsSpec.parse(dims, types)

    def open_fw(self) -> None:
        """Resolve framework + configure the sub-plugin (parity:
        gst_tensor_filter_common_open_fw, tensor_filter_common.c:2465)."""
        if self.subplugin is not None:
            return
        from ..filters.modeluri import resolve_model_uri_versioned

        # scheme-qualified model URIs (mlagent:// analog) resolve first,
        # so extension-based framework detection sees the real target;
        # a `@<tag>` version suffix resolves to (target, tag) and the
        # tag rides along as swap provenance
        self.model, self.model_version = \
            resolve_model_uri_versioned(self.model)
        fw_name = self.framework or "auto"
        if fw_name == "auto":
            fw_name = detect_framework(self.model)
        cls = find_filter(fw_name)
        fprops = FilterProps(
            framework=fw_name, model=self.model,
            accelerator=self.accelerator, custom=self.custom,
            input_spec=self._user_spec(self.input, self.inputtype),
            output_spec=self._user_spec(self.output, self.outputtype),
            shared_key=self.shared_tensor_filter_key or None,
            is_updatable=bool(self.is_updatable),
            latency_report=bool(self.latency_report),
            mesh=str(self.mesh or ""), sharding=str(self.sharding or ""),
            devices=str(self.devices or ""))
        if self.share_model:
            if self.invoke_dynamic:
                raise ValueError(
                    f"{self.name}: share-model=true cannot combine with "
                    "invoke-dynamic (per-buffer reshapes would recompile "
                    "the shared instance under every sharer)")
            # is-updatable IS allowed on a shared pool since the model
            # lifecycle layer (runtime/lifecycle.py): a RELOAD_MODEL
            # event routes through PoolEntry.reload_model — staged +
            # warmed off the dispatch path, flipped at a window
            # boundary (or canaried per the pool's canary= split) for
            # EVERY sharer at once, never one sharer's private swap
            from ..runtime.serving import MODEL_POOL, pool_key
            self._pool_entry = MODEL_POOL.acquire(
                pool_key(fw_name, fprops),
                lambda: cls.open_shared(fprops), cls.close_shared)
            self.subplugin = self._pool_entry.subplugin
        else:
            sp = cls()
            sp.configure(fprops)
            if self._fused_pre and hasattr(sp, "set_fused_pre"):
                # fusion pass inlined upstream transform chains into this
                # filter's computation (runtime/fusion.py)
                sp.set_fused_pre(self._fused_pre)
            if self._fused_post and hasattr(sp, "set_fused_post"):
                # fusion pass inlined the downstream decoder's device
                # program as the computation's epilogue
                sp.set_fused_post(self._fused_post)
            self.subplugin = sp
        self.in_spec, self.out_spec = self.subplugin.get_model_info()
        mn = getattr(self.subplugin, "model_name", None)
        if callable(mn):
            # obs join key: this element's nns_invoke_device_seconds
            # series measures executables of this model (obs/xlacost.py
            # scrape-time MFU join)
            from ..obs import xlacost as _xlacost

            _xlacost.map_source(self.name, mn())
        self._in_combi = _parse_combination(self.input_combination)
        # output-combination tokens: iN (input passthrough) / oN (model out)
        self._out_combi = [t.strip() for t in str(
            self.output_combination).split(",") if t.strip()] or None

    def start(self) -> None:
        b = int(self.batch or 1)
        if str(self.chaos or "").strip():
            from ..chaos.plan import FaultPlan

            self._chaos_plan = FaultPlan.parse(str(self.chaos))
        if self._pool_entry is not None:
            # shared-model serving: this element becomes one STREAM of
            # the pool entry.  batch* properties are pool-level — the
            # attach validates them against the settings other sharers
            # fixed, and raises on conflict (caught by Pipeline.start).
            self._pool_batched = self._pool_entry.attach(
                self, b, float(self.batch_timeout_ms), self.batch_buckets,
                slo_ms=float(self.slo_ms or 0.0),
                priority=self.priority,
                deadline_ms=float(self.deadline_ms or 0.0),
                queue_limit=int(self.queue_limit or 0),
                canary=str(self.canary or ""),
                tenant=str(self.tenant or ""))
            self._pool_attached = True
            return
        if b <= 1:
            return
        if self.invoke_dynamic:
            raise ValueError(
                f"{self.name}: batch={b} requires static shapes; "
                "invoke-dynamic streams reshape per buffer and cannot "
                "share a bucketed executable")
        from ..runtime.batching import MicroBatcher, parse_buckets

        self._buckets = parse_buckets(self.batch_buckets, b)
        self._batcher = MicroBatcher(
            max_batch=b, timeout_s=float(self.batch_timeout_ms) / 1e3,
            flush_fn=self._invoke_microbatch, error_fn=self.post_error,
            name=self.name)
        self._batcher.start()

    def stop(self) -> None:
        if self._pool_entry is not None:
            from ..runtime.serving import MODEL_POOL

            entry, self._pool_entry = self._pool_entry, None
            self._pool_batched = False
            if self._pool_attached:
                self._pool_attached = False
                try:
                    entry.detach(self)  # flushes THIS stream's parked
                    # frames; survivors keep dispatching on the entry
                except Exception as e:  # noqa: BLE001 - report, keep
                    # stopping: the refcount must still drop
                    self.post_error(e)
            MODEL_POOL.release(entry)
            self.subplugin = None
            return
        if self._batcher is not None:
            try:
                self._batcher.flush()  # drain, best effort: downstream
                # may already be stopping, but frames must not vanish
            except Exception as e:  # noqa: BLE001 - report, keep stopping
                self.post_error(e)
            self._batcher.stop()
            self._batcher = None
        if self.subplugin is not None:
            self.subplugin.close()
            self.subplugin = None

    def on_eos(self) -> None:
        # partial-batch flush BEFORE the EOS event forwards downstream:
        # no frame loss, and sinks see data-then-EOS in order
        if self._pool_entry is not None and self._pool_attached:
            try:
                # per-stream flush: only THIS stream's parked frames
                # must drain; other pipelines' windows stay open
                self._pool_entry.flush_stream(self)
            except Exception as e:  # noqa: BLE001 - same contract as the
                # per-element flush below: report, let EOS propagate
                self.post_error(e)
            return
        if self._batcher is not None:
            try:
                self._batcher.flush()
            except Exception as e:  # noqa: BLE001 - the EOS path has no
                # guarded caller (Queue._loop forwards unguarded): a
                # flush failure must reach the bus, and EOS must still
                # propagate so wait_eos() terminates
                self.post_error(e)

    # -- negotiation ---------------------------------------------------------

    def pad_template_caps(self, pad: Pad) -> Caps:
        if pad.direction.value == "sink":
            if self.invoke_dynamic:
                return Caps.any_tensors()
            try:
                self.open_fw()
            except (FilterError, KeyError, ValueError) as e:
                raise NegotiationError(f"{self.name}: open failed: {e}",
                                       reason="open", sink_pad=pad) from e
            spec = self.in_spec
            if self._in_combi is not None:
                # model sees a subset; pad accepts anything containing it
                return Caps.any_tensors()
            # Preferred: exact model input caps. Fallback: any tensors —
            # caps_negotiated then tries the SET_INPUT_INFO reshape path.
            exact = Caps.from_spec(spec)
            return Caps(structs=exact.structs + Caps.any_tensors().structs)
        return Caps.any_tensors()

    def caps_negotiated(self, pad: Pad) -> None:
        if self.invoke_dynamic:
            return
        self.open_fw()
        spec = pad.spec
        if spec is None or self._in_combi is not None:
            return
        if not spec.is_static():
            # flexible input: per-buffer schemas can't pre-compile an
            # overlay epilogue — withdraw the decoder fusion so the
            # decoder renders for itself (mirror of transform _unfuse)
            if self._fused_post:
                self._fused_post.clear()
                if self._fused_post_decoder is not None:
                    self._fused_post_decoder.fused_upstream = False
            return
        compiled = getattr(self.subplugin, "_compiled", None)
        stale_pre = compiled is not None and \
            (compiled.with_pre != bool(self._fused_pre)
             or getattr(compiled, "with_post", False)
             != bool(self._fused_post))
        if self._fused_pre or self._fused_post or stale_pre:
            # fused prologue: the executable must be specialized to the
            # RAW upstream schema even when it happens to be compatible
            # with the model's declared input; a stale executable whose
            # prologue state no longer matches (element reused after the
            # fusion pass re-derived) must recompile either way
            try:
                self.in_spec, self.out_spec = \
                    self.subplugin.set_input_info(spec)
            except FilterError as e:
                raise NegotiationError(
                    f"{self.name}: fused prologue rejects input "
                    f"{spec}: {e}") from e
            return
        if not spec.is_compatible(self.in_spec):
            if self._shared_by_others():
                # a pooled model must not be recompiled under the other
                # sharers' feet: sharers negotiate identical schemas.
                # Checked HERE because the pool opens the framework
                # instance once per key — the sub-plugin's own ref count
                # cannot see how many elements ride the pool entry.
                raise NegotiationError(
                    f"{self.name}: input {spec} incompatible with the "
                    f"shared model's {self.in_spec}, which "
                    f"{self._pool_entry.refcount - 1} other filter(s) "
                    f"depend on — share-model sharers must negotiate "
                    f"identical input schemas")
            # try a model reshape (SET_INPUT_INFO path)
            try:
                self.in_spec, self.out_spec = \
                    self.subplugin.set_input_info(spec)
            except FilterError as e:
                raise NegotiationError(
                    f"{self.name}: input {spec} incompatible with model "
                    f"{self.in_spec}: {e}") from e

    def _shared_by_others(self) -> bool:
        """Whether other elements currently hold the same pooled model
        (reshaping it would swap the executable under them)."""
        return self._pool_entry is not None and self._pool_entry.refcount > 1

    def propose_src_caps(self, pad: Pad) -> Caps:
        self.open_fw()
        rate = Fraction(0, 1)
        if self.sinkpad.spec is not None:
            rate = self.sinkpad.spec.rate
        if self.invoke_dynamic:
            return Caps.from_spec(TensorsSpec(
                format=TensorFormat.FLEXIBLE, rate=rate))
        out = self.out_spec.with_rate(rate)
        if self._out_combi is not None and self.sinkpad.spec is not None:
            out = self._combined_out_spec(self.sinkpad.spec).with_rate(rate)
        return Caps.from_spec(out)

    def _combined_out_spec(self, in_spec: TensorsSpec) -> TensorsSpec:
        """output-combination 'iN,...,oM,...' merges input passthroughs and
        model outputs (parity: tensor_filter.c:848-880)."""
        tensors = []
        for tok in str(self.output_combination).split(","):
            tok = tok.strip()
            if tok.startswith("i"):
                tensors.append(in_spec.tensors[int(tok[1:])])
            elif tok.startswith("o"):
                tensors.append(self.out_spec.tensors[int(tok[1:])])
        return TensorsSpec(tensors=tuple(tensors))

    # -- hot path ------------------------------------------------------------

    def chain(self, pad: Pad, buf: Buffer) -> None:
        sp = self.subplugin
        if sp is None:
            # checked BEFORE the QoS throttle: a misconfigured filter must
            # report, not silently drop every buffer as "throttled"
            raise StreamError(f"{self.name}: no sub-plugin opened")
        if self._throttled():
            return  # QoS drop (parity: tensor_filter.c:511)
        if self.devices:
            # stage boundary: a frame produced on ANOTHER device subset
            # hands off device-to-device BEFORE it parks in this
            # stage's window — the handoff is part of arriving at the
            # stage, never part of a dispatch
            buf = self._stage_ingress(buf)
        if self._pool_batched and self._pool_entry is not None:
            if self._chaos_plan is not None:
                # element-scoped faults on a pooled stream apply at
                # admission (the pool dispatch belongs to every sharer;
                # the process-wide plan covers it instead)
                apply_invoke_fault(self._chaos_plan, self.name)
            # shared-model serving: park the buffer in the CROSS-pipeline
            # window; the pool dispatch demuxes the result back here
            self._pool_entry.submit(self, buf)
            return
        if self._batcher is not None:
            # micro-batching: park the buffer in the coalescing window;
            # the window flush (full/deadline/EOS) dispatches it
            self._batcher.submit(buf)
            return
        if self._pool_entry is not None:
            # per-frame pooled stream: a live canary may route THIS
            # stream's frames through the staged version's instance
            sp = self._pool_entry.subplugin_for(self)
        # model-path fault seam (unbatched dispatch site): the element
        # plan AND the process-wide plan both apply — NNS_TPU_CHAOS is
        # documented to hold regardless of per-element plans
        if self._chaos_plan is not None:
            apply_invoke_fault(self._chaos_plan, self.name)
        ch = _chaos_hooks.plan
        if ch is not None:
            apply_invoke_fault(ch, self.name)
        tensors = buf.tensors
        if self._in_combi is not None:
            tensors = [tensors[i] for i in self._in_combi]
        if self.invoke_dynamic:
            self._reshape_dynamic(buf)
        device = "tpu" in sp.ACCELERATORS
        # the sample gate opens BEFORE input prep: host-prep is part of
        # what this element spends per dispatch, so the sampled invoke
        # latency (and its phase split) starts here
        sample, t0 = self._sample_gate()
        inputs = [t.jax() if device else t.np() for t in tensors]
        t1 = time.monotonic()
        if _profile.trace_active():
            # device-trace correlation: the sampled frame's trace id
            # shows up as a TraceAnnotation on the TensorBoard timeline
            with _profile.frame_annotation(_trace_ids([buf])):
                outputs = sp.invoke(inputs)
        else:
            outputs = sp.invoke(inputs)
        if getattr(sp, "_donate", False):
            # donation consumed the device-resident inputs' HBM
            # buffers: mark exactly the tensors that were PASSED to the
            # dispatch (input-combination may have excluded some — XLA
            # never saw those, so they stay valid) so any re-read (a
            # tee branch, a retained reference) raises
            # DonatedTensorError instead of reading reused memory
            for t in tensors:
                t.mark_donated()
        t2 = self._record_dispatch(outputs, t0, frames=1, sample=sample)
        out_tensors = [Tensor(o) for o in outputs]
        if self._out_combi is not None:
            out_tensors = self._combine_outputs(buf, out_tensors)
        meta = dict(buf.meta)
        if meta.pop(_STAGE_META, None):
            # the handed-off frame leaves the stage: depth decrement
            _stagestat.record_emit(
                self.pipeline.name if self.pipeline is not None else "",
                self.name)
        out = Buffer(tensors=out_tensors, pts=buf.pts, duration=buf.duration,
                     offset=buf.offset, meta=meta,
                     format=TensorFormat.FLEXIBLE if self.invoke_dynamic
                     else TensorFormat.STATIC)
        if sample:
            # cost attribution: phases recorded (and trace marks
            # planted) BEFORE the push — the sink finalizes the trace
            # record inline during it
            t3 = time.monotonic()
            self._attribute_phases(t0, t1, t2, t3, bucket=1)
            tracer = _hooks.tracer
            if tracer is not None:
                tracer.invoke_split([(self.name, out)], t0, t1, t2, t3)
        self.push(out)

    # -- stage boundary (disaggregated pipeline split) -----------------------

    def _stage_ingress(self, buf: Buffer) -> Buffer:
        """Cross-subset handoff INTO this stage: when this filter's
        resolved placement pins an explicit ``devices=`` subset and the
        frame's tensors live on chips OUTSIDE it (the upstream stage's
        subset), route the frame through the device channel's slot
        semantics re-homed onto this stage's devices — a device-to-
        device ICI copy with one byte-exact ``d2d`` ledger row, never a
        host bounce, so ``crossings_per_frame`` stays 0.0 across the
        boundary.  Host/mixed frames pass through untouched (their
        upload is the ordinary ``h2d`` path), as do frames already
        resident on this stage's chips."""
        rp = getattr(self.subplugin, "_placement", None)
        if rp is None or not getattr(rp, "stage", ""):
            return buf
        mine = set(rp.device_ids)
        src_ids: set = set()
        for t in buf.tensors:
            if t.is_device:
                src_ids.update(_device_ids_of(t))
        if not src_ids or src_ids <= mine:
            return buf  # already local to this stage (or host-only)
        from ..edge import devicechannel as _devch
        from ..parallel.placement import subset_label

        if not _devch.eligible(buf):
            return buf  # mixed residency: plain upload path
        nbytes = buf.nbytes
        # re-home onto the WHOLE stage mesh (replicated sharding), not
        # one chip: a jit argument committed to a single device is
        # incompatible with the stage's sharded window dispatch (the
        # batched executable constrains the stacked window over the
        # subset's data axis — committed devices must match the mesh)
        target = rp.mesh.devices.flat[0]
        try:
            import jax

            target = jax.sharding.NamedSharding(
                rp.mesh, jax.sharding.PartitionSpec())
        except Exception:  # noqa: BLE001 - single-chip re-home fallback
            pass
        out = _devch.stage_handoff(buf, target,
                                   chan=("stage", self.name))
        out.meta[_STAGE_META] = True
        _stagestat.record_handoff(
            self.pipeline.name if self.pipeline is not None else "",
            self.name, subset_label(src_ids), rp.stage, 1, nbytes)
        return out

    # -- dispatch timing (shared by every invoke path) -----------------------

    def _sample_gate(self):
        """Decide whether this dispatch is a blocking stats sample and, if
        so, drain the async backlog of earlier invokes first — so t0→done
        times ONE dispatch, not the queued N-1 plus this one.  Returns
        ``(sample, t0)``."""
        if _hooks.DISABLED:
            # NNS_TPU_OBS_DISABLE: the dispatch path is FULLY async —
            # no seq/interval bookkeeping, no backlog drain, and (via
            # _record_dispatch) no _last_out retention pinning a
            # window's outputs in HBM.  stat-sample-interval-ms and
            # latency=1 no-op under the kill switch (nns-lint NNS508
            # warns about exactly that combination).
            return False, time.monotonic()
        self._invoke_seq += 1
        now = time.monotonic()
        interval = self.STAT_SAMPLE_INTERVAL \
            if self.stat_sample_interval_ms is None \
            else float(self.stat_sample_interval_ms) / 1e3
        sample = (bool(self.latency) or self._invoke_seq == 1 or
                  now - self._last_sample_ts >= interval)
        if sample and self._last_out is not None:
            block_all([self._last_out])
        return sample, time.monotonic()

    def _record_dispatch(self, outs: List[Any], t0: float,
                         frames: int = 1, sample: bool = True) -> float:
        """Post-invoke bookkeeping shared by the single-frame and
        micro-batched paths: on a sampled dispatch, block on ALL its
        outputs so the recorded time covers device execution (parity:
        tensor_filter.c:389-468 measures the actual invoke — and a
        multi-output model may still be executing earlier outputs when
        the last one resolves); otherwise just count, since unsampled
        invokes would systematically report enqueue time on TPU.  Keeps
        the drain point for the next sample and posts LATENCY messages.
        ``outs`` is the flat list of every output array of the
        dispatch.  Returns the device-done timestamp — the SAME clock
        read the latency was recorded from, so the cost-attribution
        phases partition the recorded latency exactly."""
        if sample:
            block_all(outs)
            t2 = time.monotonic()
            self.invoke_stats.record(t2 - t0, frames=frames)
            self._last_sample_ts = t2
        else:
            t2 = time.monotonic()
            self.invoke_stats.count(frames=frames)
        # the drain anchor for the NEXT sample — with observability
        # killed there will never be one, so don't pin a window's
        # output in HBM until the stream's next dispatch
        self._last_out = (outs[-1] if outs else None) \
            if not _hooks.DISABLED else None
        if self.latency_report:
            rep = self.invoke_stats.latency_to_report()
            if rep is not None:
                self.post_message(Message(
                    MessageKind.LATENCY, self.name, data={"latency_us": rep}))
        return t2

    def _attribute_phases(self, t0: float, t1: float, t2: float,
                          t3: float, bucket: int) -> None:
        """Record one sampled dispatch's host-prep (t0→t1) / device
        (t1→t2) / host-drain (t2→t3) split into the element's
        InvokeStats and the registry's ``nns_invoke_*`` histograms.
        t2 is the block_until_ready fence ``_record_dispatch``
        returned, so prep + device equals the recorded invoke latency
        by construction."""
        from ..obs.metrics import observe_invoke_phases

        self.invoke_stats.record_phases(t1 - t0, t2 - t1, t3 - t2)
        observe_invoke_phases("element", self.name, bucket,
                              t1 - t0, t2 - t1, t3 - t2)

    def _invoke_microbatch(self, bufs: List[Buffer]) -> None:
        """Window flush: dispatch 1..batch queued buffers as one XLA
        invoke (padded to a bucket), then unbatch the outputs back into
        per-frame Buffers in arrival order, pts/offset/meta preserved.
        Runs on the producer thread (full window) or the coalescer's
        timer thread (deadline/EOS) — never concurrently (MicroBatcher
        serializes flushes)."""
        sp = self.subplugin
        if sp is None:
            raise StreamError(f"{self.name}: no sub-plugin opened")
        # model-path fault seam (micro-batched dispatch site): a
        # fail-invoke loses the whole window, like a real XLA error;
        # element plan and process-wide plan BOTH apply
        if self._chaos_plan is not None:
            apply_invoke_fault(self._chaos_plan, self.name)
        ch = _chaos_hooks.plan
        if ch is not None:
            apply_invoke_fault(ch, self.name)
        # sample gate BEFORE frame prep: host-prep (input gather +
        # conversion for the whole window) is part of the dispatch cost
        sample, t0 = self._sample_gate()
        # transfer-label context for the window: deadline/EOS flushes
        # run on the coalescer's timer thread, which carries no chain
        # context — the window's crossings still belong to this element
        xctx = None
        pushed = _xfer.ACTIVE
        if pushed:
            traces = tuple(
                tr for tr in (b.meta.get(TRACE_META_KEY) for b in bufs)
                if tr is not None) or None
            xctx = _xfer.push_context(
                self.pipeline.name if self.pipeline is not None else "",
                self.name, traces)
        try:
            self._invoke_microbatch_inner(bufs, sample, t0)
        finally:
            if pushed:
                _xfer.pop_context(xctx)

    def _invoke_microbatch_inner(self, bufs: List[Buffer], sample: bool,
                                 t0: float) -> None:
        from ..runtime.batching import pick_bucket

        sp = self.subplugin
        frames = [self._pool_frame_inputs(buf) for buf in bufs]
        bucket = pick_bucket(len(frames), self._buckets)
        t1 = time.monotonic()
        # device-trace correlation: the window's sampled trace ids ride
        # the dispatch as a TraceAnnotation (no-op without an active
        # jax profiler capture — guarded to keep the hot path free)
        with _profile.frame_annotation(_trace_ids(bufs)) \
                if _profile.trace_active() else _nullcontext():
            if getattr(sp, "SUPPORTS_BATCH", False):
                outs = sp.invoke_batched(frames, bucket)
            else:
                # framework without a batched entry point: the window
                # still coalesces (ordering, EOS flush, occupancy
                # stats) but each frame dispatches separately
                outs = [sp.invoke(list(f)) for f in frames]
        if getattr(sp, "SUPPORTS_BATCH", False) and \
                getattr(sp, "_donate", False):
            # same donation bookkeeping as the single-frame path (the
            # batched executable donates its window args; pad-slot
            # replays are copies, so only the real frames are
            # consumed), restricted to the input-combination subset
            # actually fed to the dispatch
            for buf in bufs:
                ts = buf.tensors
                if self._in_combi is not None:
                    ts = [ts[i] for i in self._in_combi]
                for t in ts:
                    t.mark_donated()
        t2 = self._record_dispatch([o for out in outs for o in out], t0,
                                   frames=len(bufs), sample=sample)
        if sample:
            tracer = _hooks.tracer
            if tracer is not None:
                # marks planted BEFORE the demux (sinks reached inline
                # finalize the records); each buffer's own demux mark
                # closes its drain span
                tracer.invoke_split([(self.name, b) for b in bufs],
                                    t0, t1, t2)
        for buf, out in zip(bufs, outs):
            self._pool_emit(buf, out)
        if sample:
            # host-drain of the window: unbatch + per-frame wrap + the
            # downstream handoff of every frame demuxed above
            self._attribute_phases(t0, t1, t2, time.monotonic(),
                                   bucket=bucket)

    # -- serving-pool hooks (runtime/serving.py drives these) ----------------

    def _pool_frame_inputs(self, buf: Buffer) -> List[Any]:
        """Model inputs of one parked frame, input-combination applied.
        Device-resident tensors pass through as jax arrays; host-resident
        ones stay numpy — the batched executable's own arg handling
        transfers them, which is cheaper than a separate per-frame upload
        dispatch ahead of the invoke."""
        tensors = buf.tensors
        if self._in_combi is not None:
            tensors = [tensors[i] for i in self._in_combi]
        return [t.jax() if t.is_device else t.np() for t in tensors]

    def _pool_emit(self, buf: Buffer, out: List[Any]) -> None:
        """Demux one dispatch result onto THIS filter's downstream pad —
        the owner's flush context: output-combination, pts/offset/meta
        preservation, and any downstream failure surfacing on THIS
        element's bus."""
        tracer = _hooks.tracer
        if tracer is not None:
            tracer.batch_demuxed(self, buf)
        out_tensors = [Tensor(o) for o in out]
        if self._out_combi is not None:
            out_tensors = self._combine_outputs(buf, out_tensors)
        meta = dict(buf.meta)
        if meta.pop(_STAGE_META, None):
            # the handed-off frame leaves the stage: depth decrement
            _stagestat.record_emit(
                self.pipeline.name if self.pipeline is not None else "",
                self.name)
        self.push(Buffer(
            tensors=out_tensors, pts=buf.pts, duration=buf.duration,
            offset=buf.offset, meta=meta,
            format=TensorFormat.STATIC))

    def _combine_outputs(self, in_buf: Buffer, outputs: List[Tensor]
                         ) -> List[Tensor]:
        combined = []
        for tok in str(self.output_combination).split(","):
            tok = tok.strip()
            if tok.startswith("i"):
                combined.append(in_buf.tensors[int(tok[1:])])
            elif tok.startswith("o"):
                combined.append(outputs[int(tok[1:])])
        return combined

    def _reshape_dynamic(self, buf: Buffer) -> None:
        spec = buf.spec()
        if self._dyn_spec is not None and spec.is_compatible(self._dyn_spec):
            return
        self.in_spec, self.out_spec = self.subplugin.set_input_info(spec)
        self._dyn_spec = spec

    def _throttled(self) -> bool:
        if self._throttle_interval <= 0:
            return False
        now = time.monotonic()
        if now - self._last_invoke_ts < self._throttle_interval:
            return True
        self._last_invoke_ts = now
        return False

    # -- events --------------------------------------------------------------

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.QOS_THROTTLE:
            rate = event.data.get("rate")
            self._throttle_interval = float(1 / rate) if rate else 0.0
        super().handle_upstream_event(pad, event)

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.RELOAD_MODEL:
            if self._pool_entry is not None:
                # shared pool: the reload steers the POOL through the
                # lifecycle layer — staged + warmed off the dispatch
                # path, then hot-swapped at a window boundary (or
                # canaried per the pool's canary= declaration)
                if not self.is_updatable:
                    self.post_error(FilterError(
                        f"{self.name}: model is not updatable"))
                    return
                from ..runtime.actuators import ActuationError
                from ..runtime.lifecycle import LifecycleError

                try:
                    self._pool_entry.reload_model(
                        event.data["model"],
                        version=str(event.data.get("version", "")))
                except (FilterError, ActuationError,
                        LifecycleError, ValueError) as e:
                    self.post_error(e)
                return
            try:
                self.subplugin.handle_event(event)
                self.in_spec, self.out_spec = self.subplugin.get_model_info()
            except FilterError as e:
                self.post_error(e)
            return
        super().handle_event(pad, event)

    # -- introspection props -------------------------------------------------

    @property
    def latency_us(self) -> int:
        return self.invoke_stats.latency_us

    @property
    def throughput_milli_fps(self) -> int:
        return self.invoke_stats.throughput_milli_fps

    @property
    def dispatch_milli_fps(self) -> int:
        """1000×XLA dispatches/s — below throughput_milli_fps exactly
        when micro-batching is coalescing."""
        return self.invoke_stats.dispatch_milli_fps

    @property
    def batch_occupancy(self) -> float:
        """Realized mean frames per dispatch (1.0 unbatched)."""
        return self.invoke_stats.avg_batch_occupancy

    # -- serving-pool introspection ------------------------------------------

    @property
    def pool(self):
        """The shared serving-pool entry (``share-model=true``), else
        None.  Its ``stats`` carry the TRUE cross-pipeline dispatch
        counts; this element's own ``invoke_stats`` count the dispatches
        its frames rode in."""
        return self._pool_entry

    @property
    def pool_streams(self) -> int:
        """Streams currently attached to the shared pool entry (0 when
        not sharing)."""
        return self._pool_entry.attached_streams \
            if self._pool_entry is not None else 0

    @property
    def pool_stream_occupancy(self) -> float:
        """Mean distinct pipelines per shared dispatch (0.0 when not
        sharing)."""
        return self._pool_entry.stats.avg_stream_occupancy \
            if self._pool_entry is not None else 0.0

    # -- multi-chip bookkeeping (round-3 verdict #7) -------------------------

    @property
    def num_shards(self) -> int:
        """Mesh size when the sub-plugin compiled over a mesh=; 1 on a
        single device."""
        mesh = getattr(self.subplugin, "_mesh", None)
        return int(mesh.devices.size) if mesh is not None else 1

    @property
    def data_shards(self) -> int:
        """LOCAL batch parallelism of the sub-plugin's placement: the
        per-process share of the data axes (this element's
        ``invoke_stats`` count only this process's frames, so dividing
        them by the global product would understate per-chip
        throughput by the process count on a multi-host placement); 1
        without a mesh.  Falls back to the single ``_data_axis`` view,
        then to the full mesh size, when the sub-plugin predates the
        placement layer."""
        rp = getattr(self.subplugin, "_placement", None)
        if rp is not None:
            return int(rp.local_data_axis_size)
        mesh = getattr(self.subplugin, "_mesh", None)
        if mesh is None:
            return 1
        axis = getattr(self.subplugin, "_data_axis", None)
        if axis is not None:
            try:
                return int(mesh.shape[axis])
            except (KeyError, AttributeError):
                pass
        return int(mesh.devices.size)

    @property
    def throughput_per_shard_milli_fps(self) -> int:
        """Per-chip share of the element's throughput along the DATA
        axis: each chip handles batch/data_shards of every invoke
        (chips on a model-parallel axis all process the same samples,
        so dividing by the full mesh size would understate scaling
        efficiency by the model-axis factor)."""
        return self.invoke_stats.throughput_milli_fps // \
            max(self.data_shards, 1)


class FilterSingle:
    """Invoke a filter sub-plugin without a pipeline (parity:
    tensor_filter_single.c — basis of the ML single-shot API)."""

    def __init__(self, framework: str = "auto", model: Any = None, **kw):
        from ..filters.modeluri import resolve_model_uri

        model = resolve_model_uri(model)
        fw = framework if framework != "auto" else detect_framework(model)
        self.subplugin = find_filter(fw)()
        self.subplugin.configure(FilterProps(framework=fw, model=model, **kw))
        self.in_spec, self.out_spec = self.subplugin.get_model_info()
        self.stats = InvokeStats()

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        t0 = time.monotonic()
        out = self.subplugin.invoke(list(inputs))
        # single-shot is a synchronous API: stats cover execution
        block_all(out)
        self.stats.record(time.monotonic() - t0)
        return out

    def set_input_info(self, spec: TensorsSpec) -> None:
        self.in_spec, self.out_spec = self.subplugin.set_input_info(spec)

    def close(self) -> None:
        self.subplugin.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""Structured logging with element provenance.

Parity target: /root/reference/gst/nnstreamer/nnstreamer_log.c:35-45
(``ml_logi/logw/loge/logf`` + stacktrace on fatal errors).  ``loge_stacktrace``
attaches a formatted Python traceback the way the reference attaches a glibc
``backtrace()``.
"""

from __future__ import annotations

import logging
import os
import traceback

_LOGGER = logging.getLogger("nnstreamer_tpu")
if not _LOGGER.handlers:
    h = logging.StreamHandler()
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname).1s nnstreamer_tpu[%(element)s] %(message)s",
        defaults={"element": "-"}))
    _LOGGER.addHandler(h)
    _LOGGER.setLevel(os.environ.get("NNS_TPU_LOG_LEVEL", "WARNING").upper())

ISSUE_URL = "https://github.com/nnstreamer/nnstreamer/issues"


def _log(level: int, msg: str, *args, element: str = "-") -> None:
    _LOGGER.log(level, msg, *args, extra={"element": element})


def logd(msg, *args, element="-"):
    _log(logging.DEBUG, msg, *args, element=element)


def logi(msg, *args, element="-"):
    _log(logging.INFO, msg, *args, element=element)


def logw(msg, *args, element="-"):
    _log(logging.WARNING, msg, *args, element=element)


def loge(msg, *args, element="-"):
    _log(logging.ERROR, msg, *args, element=element)


def loge_stacktrace(msg, *args, element="-"):
    _log(logging.ERROR, msg + "\n" + "".join(traceback.format_stack()),
         *args, element=element)


def logf(msg, *args, element="-"):
    _log(logging.CRITICAL, msg, *args, element=element)

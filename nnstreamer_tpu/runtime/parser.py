"""gst-launch-style pipeline description parser.

``parse_launch`` builds a Pipeline from strings like::

    appsrc name=src ! tensor_converter ! tensor_transform mode=typecast
      option=float32 ! tensor_filter framework=jax-xla model=net.pkl !
      tensor_sink name=out

Supported syntax (the subset the reference's pipelines and tests rely on —
see /root/reference/Documentation/gst-launch-script-example.md):
- ``factory prop=value ...`` element segments, ``!`` links
- ``name=...`` names an element; ``somename.`` / ``somename.padname``
  references an existing element (request pads resolved on demand)
- bare caps strings (``other/tensors,format=static,...``) insert an implicit
  capsfilter
- quoted property values via shlex rules
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional, Tuple, Union

from ..core import Caps, CapsStruct
from .element import Element, Pad, PadDirection
from .pipeline import Pipeline
from .registry import make, register_element


class ParseError(Exception):
    """Pipeline/caps description error.

    ``pos`` (when known) is the 0-based character offset of the offending
    token in the parsed string, so tooling can point at the exact spot;
    for single-line descriptions it doubles as the column.  Use
    :meth:`context` to render a caret marker.  ``kind`` is a stable
    symbolic cause for tooling (``"double-link"`` today; messages are for
    humans and may be reworded)."""

    def __init__(self, message: str, pos: Optional[int] = None,
                 kind: Optional[str] = None):
        super().__init__(message)
        self.pos = pos
        self.kind = kind

    @property
    def column(self) -> Optional[int]:
        return self.pos

    def context(self, desc: str, width: int = 60) -> str:
        """Render the description with a ``^`` caret under ``pos``."""
        if self.pos is None:
            return desc[:width]
        lo = max(0, self.pos - width // 2)
        frag = desc[lo:lo + width]
        return frag + "\n" + " " * (self.pos - lo) + "^"


def parse_caps_string(s: str, base_pos: int = 0) -> Caps:
    """Parse ``mime,key=value,...``; values may be ints, fractions, or
    strings; ``{a,b}`` denotes a set.  ``base_pos`` offsets error positions
    when the caps string is embedded in a larger description."""
    parts = _split_caps_fields(s)
    offs = []
    off = 0
    for part in parts:  # recover each field's offset within s
        offs.append(off)
        off += len(part) + 1  # the separating comma
    mime = parts[0].strip()
    fields = {}
    for kv, kvoff in zip(parts[1:], offs[1:]):
        if "=" not in kv:
            raise ParseError(f"bad caps field {kv!r} in {s!r}",
                             pos=base_pos + kvoff)
        k, v = kv.split("=", 1)
        k = k.strip()
        if k in ("dimensions", "types", "format"):
            # grammar fields stay strings: a scalar like dimensions=1 must
            # not become int (it would break the dimensions special-case in
            # caps intersection, which is string-typed)
            fields[k] = v.strip().strip('"')
        else:
            fields[k] = _parse_value(v.strip())
    return Caps.new(CapsStruct.make(mime, **fields))


def _split_caps_fields(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _parse_value(v: str):
    v = v.strip().strip('"')
    if v.startswith("{") and v.endswith("}"):
        return frozenset(_parse_value(x) for x in v[1:-1].split(","))
    if "/" in v:
        a, _, b = v.partition("/")
        if a.strip().lstrip("-").isdigit() and b.strip().isdigit():
            return Fraction(int(a), int(b))
    if v.lstrip("-").isdigit():
        return int(v)
    low = v.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    try:
        return float(v)  # 0.5, 1e-3 — gst-launch float properties
    except ValueError:
        return v


@register_element("capsfilter")
class CapsFilter(Element):
    """Pass-through element that constrains negotiation to its caps."""

    FACTORY = "capsfilter"

    def __init__(self, name=None, caps: Optional[Union[Caps, str]] = None,
                 **props):
        self.caps = caps
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            self.caps = parse_caps_string(self.caps)
        self.add_sink_pad()
        self.add_src_pad()

    def pad_template_caps(self, pad: Pad) -> Caps:
        return self.caps if self.caps is not None else Caps.any_tensors()

    def propose_src_caps(self, pad: Pad) -> Caps:
        base = super().propose_src_caps(pad)
        return base.intersect(self.caps) if self.caps is not None else base

    def chain(self, pad: Pad, buf) -> None:
        self.push(buf)


class _Segment:
    __slots__ = ("kind", "value", "props", "pad", "pos")

    def __init__(self, kind, value, props=None, pad=None, pos=None):
        self.kind = kind  # 'element' | 'ref' | 'caps'
        self.value = value
        self.props = props or {}
        self.pad = pad
        self.pos = pos  # character offset of the segment's first token


def _tokenize(desc: str) -> List[Tuple[str, int]]:
    """Split on whitespace with posix-shlex quoting rules, keeping each
    token's character offset in ``desc`` (so parse errors can point at the
    exact spot).  Returns ``[(token, offset), ...]``."""
    toks: List[Tuple[str, int]] = []
    i, n = 0, len(desc)
    while i < n:
        while i < n and desc[i].isspace():
            i += 1
        if i >= n:
            break
        start = i
        buf: List[str] = []
        while i < n and not desc[i].isspace():
            ch = desc[i]
            if ch in ("'", '"'):
                quote = ch
                i += 1
                while i < n and desc[i] != quote:
                    if quote == '"' and desc[i] == "\\" and i + 1 < n \
                            and desc[i + 1] in ('"', "\\"):
                        i += 1
                    buf.append(desc[i])
                    i += 1
                if i >= n:
                    raise ParseError(
                        f"unterminated {quote} quote", pos=start)
                i += 1
            elif ch == "\\" and i + 1 < n:
                buf.append(desc[i + 1])
                i += 2
            else:
                buf.append(ch)
                i += 1
        toks.append(("".join(buf), start))
    return toks


def parse_launch(desc: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    pipe = pipeline or Pipeline()
    tokens = _tokenize(desc)
    if not tokens:
        raise ParseError("empty pipeline description")

    # split into chains at '!' boundaries, building segments
    chains: List[List[_Segment]] = [[]]
    i = 0
    auto_id = [0]

    def new_name(factory: str) -> str:
        while True:
            n = f"{factory}{auto_id[0]}"
            auto_id[0] += 1
            if n not in pipe.elements:
                return n

    while i < len(tokens):
        tok, pos = tokens[i]
        if tok == "!":
            i += 1
            continue
        # gather props until next '!' or end
        props = {}
        j = i + 1
        while j < len(tokens) and tokens[j][0] != "!":
            if "=" not in tokens[j][0]:
                break
            k, v = tokens[j][0].split("=", 1)
            props[k] = _parse_value(v)
            j += 1
        if "/" in tok and "=" not in tok.split(",")[0]:
            seg = _Segment("caps", tok, pos=pos)
        elif tok.endswith(".") or ("." in tok and "=" not in tok):
            el, _, padname = tok.partition(".")
            seg = _Segment("ref", el, pad=padname or None, pos=pos)
        else:
            seg = _Segment("element", tok, props, pos=pos)
        chains[-1].append(seg)
        i = j
        # a segment not followed by '!' starts a new chain
        if i < len(tokens) and tokens[i][0] != "!":
            chains.append([])
        elif i >= len(tokens):
            break
        else:
            i += 1  # skip '!'

    # instantiate and link
    for chain in chains:
        prev: Optional[Tuple[Element, Optional[str]]] = None
        for seg in chain:
            if seg.kind == "element":
                nm = seg.props.pop("name", None) or new_name(seg.value)
                # config-file applies AFTER the other keys of this
                # segment and never overrides them: explicit
                # pipeline-string values win over the file
                cfg = seg.props.pop("config-file", None) or \
                    seg.props.pop("config_file", None)
                try:
                    el = make(seg.value, el_name=str(nm), **{
                        k.replace("-", "_"): v
                        for k, v in seg.props.items()})
                except KeyError as e:
                    # keep the message registry-independent (stable for
                    # golden output); `python -m nnstreamer_tpu.check`
                    # lists the known factories
                    raise ParseError(
                        f"unknown element factory {seg.value!r}",
                        pos=seg.pos) from e
                except ValueError as e:
                    raise ParseError(
                        f"{seg.value}: {e}", pos=seg.pos) from e
                if cfg:
                    el.load_config_file(str(cfg), skip=seg.props.keys())
                pipe.add(el)
                cur: Tuple[Element, Optional[str]] = (el, None)
            elif seg.kind == "caps":
                # positions are relative to the dequoted token; skip a
                # leading quote so field offsets land on the right char
                # (inner escapes can still drift — tokens rarely have any)
                base = seg.pos
                if base is not None and base < len(desc) \
                        and desc[base] in "'\"":
                    base += 1
                caps = parse_caps_string(seg.value, base_pos=base)
                el = CapsFilter(name=new_name("capsfilter"), caps=caps)
                pipe.add(el)
                cur = (el, None)
            else:  # ref
                if seg.value not in pipe.elements:
                    raise ParseError(
                        f"unknown element reference {seg.value!r}",
                        pos=seg.pos)
                cur = (pipe.elements[seg.value], seg.pad)
            if prev is not None:
                _link(prev, cur, pos=seg.pos)
            prev = cur
    return pipe


def _link(a: Tuple[Element, Optional[str]], b: Tuple[Element, Optional[str]],
          pos: Optional[int] = None) -> None:
    ael, apad = a
    bel, bpad = b
    try:
        src = ael.get_pad(apad) if apad \
            else _free_pad(ael, PadDirection.SRC, pos)
        sink = bel.get_pad(bpad) if bpad \
            else _free_pad(bel, PadDirection.SINK, pos)
    except KeyError as e:
        raise ParseError(
            e.args[0] if e.args else str(e), pos=pos) from e
    try:
        src.link(sink)
    except ValueError as e:
        # double link: surface as a parse error pointing at the segment
        raise ParseError(str(e), pos=pos, kind="double-link") from e


def _free_pad(el: Element, direction: PadDirection,
              pos: Optional[int] = None) -> Pad:
    pads = el.srcpads if direction == PadDirection.SRC else el.sinkpads
    for p in pads:
        if p.peer is None:
            return p
    rp = el.request_pad("src_%u" if direction == PadDirection.SRC
                        else "sink_%u")
    if rp is not None:
        return rp
    raise ParseError(f"{el.name}: no free {direction.value} pad "
                     f"(all pads already linked)", pos=pos,
                     kind="double-link")

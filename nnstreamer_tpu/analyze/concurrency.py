"""Pass 4 — whole-package concurrency analysis (NNS6xx).

Pure-AST, whole-program: every file of the package is parsed once, every
``threading.Lock/RLock/Condition`` construction becomes a *lock site*,
and a conservative inter-procedural walk of ``with <lock>:`` bodies
(following ``self.method()`` / ``self.attr.method()`` / module-function
calls within the package) builds the **lock-acquisition graph**: an edge
``A -> B`` means some code path can take ``B`` while holding ``A``.
Locks are keyed at *class granularity* (``Controller._lock``), the same
abstraction kernel lockdep uses — two instances of one class share a
key, so the graph describes lock *order*, not individual objects.

- **NNS601** a cycle in the acquisition graph: two code paths take the
  same pair of locks in opposite orders — a potential deadlock.  Both
  acquisition paths are printed.  Self-edges (re-acquiring the same
  class-keyed lock) are not reported: for ``RLock`` they are legal, and
  for distinct instances of one class they are order-unobservable here.
- **NNS602** hold-and-block: a call that can block indefinitely —
  socket ``recv/recvfrom/accept/sendall``, ``Event.wait``/``join``,
  ``select``, ``block_until_ready``, registry ``snapshot()`` — made (or
  reachable through package calls) while a lock is held.  Waiting on
  the *same* condition the ``with`` holds is exempt (``Condition.wait``
  releases it).
- **NNS603** unguarded shared state: an attribute assigned both from a
  ``Thread(target=self._x)`` entry point and from a public method, with
  at least one of the writes outside any lock.
- **NNS604** leaf-lock discipline: a lock whose construction line
  carries ``# nns-lock: leaf`` promises to never be held across another
  acquisition (that promise is what makes it safe to take from *any*
  context, e.g. the PR 11 control audit lock on the scrape path).
  Acquiring any other lock — directly or through a call — while a
  declared leaf is held breaks the promise.

Suppressions use the shared grammar (``# nns-lint: disable=NNS602 --
reason``, see :mod:`.codelint`).  The analysis also exports the graph
itself (:class:`LockGraph`: nodes/edges/sites, ``--json`` /``--dot``)
so tools can render what the runtime witness (``utils/lockdep.py``)
later confirms or refutes.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .codelint import _Suppressions, _lockish, _unparse
from .diagnostics import Diagnostic, sort_diagnostics

#: attribute calls that can block indefinitely while a lock is held
_BLOCK_ATTRS = {"recv", "recvfrom", "accept", "sendall", "join",
                "select", "block_until_ready"}
#: ``.wait``/``.wait_for`` block too, modulo the Condition exemption
_WAIT_ATTRS = {"wait", "wait_for"}
#: receiver names that mark ``snapshot()`` as the registry scrape
_REGISTRYISH = re.compile(r"registry", re.IGNORECASE)
#: ``<mod>.join`` receivers that are path math, not thread joins
_PATH_MODULES = {"os.path", "posixpath", "ntpath", "pathlib"}
#: ``# nns-lock: leaf`` on a lock construction line declares a leaf lock
_LEAF_RE = re.compile(r"#\s*nns-lock:\s*leaf\b")
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
#: call-following depth cap: beyond this the summary is treated as empty
_MAX_DEPTH = 8

_SYNC_CTORS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
               "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
               "LifoQueue", "PriorityQueue", "deque", "local"}


class LockSite:
    """One lock *key* (class-or-module granularity) plus where it is
    constructed.  ``leaf`` means the construction line declared
    ``# nns-lock: leaf``."""

    __slots__ = ("key", "kind", "display", "line", "leaf")

    def __init__(self, key: str, kind: str, display: str, line: int,
                 leaf: bool = False):
        self.key = key
        self.kind = kind
        self.display = display
        self.line = line
        self.leaf = leaf


class LockGraph:
    """The exported acquisition graph: ``nodes`` keyed like
    ``Controller._lock`` / ``pkg/mod.py:_HUB_LOCK``, ``edges`` with the
    example acquisition path that created them."""

    def __init__(self):
        self.nodes: Dict[str, LockSite] = {}
        self.edges: Dict[Tuple[str, str], dict] = {}

    def node(self, site: LockSite) -> LockSite:
        return self.nodes.setdefault(site.key, site)

    def edge(self, src: str, dst: str, path: List[str]) -> None:
        e = self.edges.get((src, dst))
        if e is None:
            self.edges[(src, dst)] = {"src": src, "dst": dst,
                                      "path": list(path), "count": 1}
        else:
            e["count"] += 1

    def as_graph_dict(self) -> dict:
        return {
            "nodes": [
                {"key": s.key, "kind": s.kind, "leaf": s.leaf,
                 "site": f"{s.display}:L{s.line}"}
                for s in sorted(self.nodes.values(),
                                key=lambda s: s.key)],
            "edges": [
                {"src": e["src"], "dst": e["dst"], "count": e["count"],
                 "path": e["path"]}
                for e in sorted(self.edges.values(),
                                key=lambda e: (e["src"], e["dst"]))],
        }

    def to_dot(self) -> str:
        lines = ['digraph "lock-order" {', "  rankdir=LR;",
                 "  node [shape=box, fontsize=10];"]
        for s in sorted(self.nodes.values(), key=lambda s: s.key):
            shape = ', style=bold, color="darkgreen"' if s.leaf else ""
            lines.append(
                f'  "{s.key}" [label="{s.key}\\n{s.kind} '
                f'{s.display}:L{s.line}"{shape}];')
        for e in sorted(self.edges.values(),
                        key=lambda e: (e["src"], e["dst"])):
            lines.append(f'  "{e["src"]}" -> "{e["dst"]}" '
                         f'[label="{e["count"]}", fontsize=8];')
        lines.append("}")
        return "\n".join(lines)

    def cycles(self) -> List[List[str]]:
        """Simple cycles of length >= 2, deduplicated by node set."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                adj.setdefault(a, []).append(b)
        seen: Set[frozenset] = set()
        out: List[List[str]] = []
        for (a, b) in sorted(self.edges):
            if a == b:
                continue
            path = self._find_path(adj, b, a)
            if path is None:
                continue
            cyc = [a] + path  # path = [b, ..., a]: closes at a
            key = frozenset(cyc)
            if key in seen:
                continue
            seen.add(key)
            out.append(cyc)
        return out

    def _find_path(self, adj, start: str, goal: str
                   ) -> Optional[List[str]]:
        stack = [(start, [start])]
        visited = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in adj.get(node, ()):
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


# -- per-file model ----------------------------------------------------------


class _Fn:
    __slots__ = ("node", "display", "cls", "qual")

    def __init__(self, node, display, cls):
        self.node = node
        self.display = display
        self.cls = cls  # class name or None for module functions
        self.qual = (f"{cls}.{node.name}" if cls else node.name)


class _Cls:
    __slots__ = ("name", "display", "bases", "methods", "attr_types",
                 "lock_attrs", "thread_targets")

    def __init__(self, name, display, bases):
        self.name = name
        self.display = display
        self.bases = bases
        self.methods: Dict[str, _Fn] = {}
        self.attr_types: Dict[str, str] = {}
        self.lock_attrs: Dict[str, LockSite] = {}
        self.thread_targets: Set[str] = set()


def _ann_name(ann) -> Optional[str]:
    """Extract a class name from an annotation AST (unwraps Optional[X],
    "X" string forms)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("[")[-1].rstrip("]").split(".")[-1] or None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Subscript):  # Optional[X] / List[X]
        return _ann_name(ann.slice)
    return None


def _lock_ctor_kind(value: ast.expr) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` → "Lock" (etc.), else None."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name if name in _LOCK_CTORS else None


def _sync_ctor(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name in _SYNC_CTORS


class _File:
    __slots__ = ("display", "source", "lines", "tree", "suppress",
                 "classes", "funcs", "module_locks", "import_mods",
                 "import_origin")

    def __init__(self, display: str, source: str):
        self.display = display
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=display)
        self.suppress = _Suppressions(source)
        self.classes: List[_Cls] = []
        self.funcs: Dict[str, _Fn] = {}
        self.module_locks: Dict[str, LockSite] = {}
        #: local alias -> module basename ("watch") for in-package
        #: ``from ..obs import watch as _watch`` style imports
        self.import_mods: Dict[str, str] = {}
        #: local alias -> (source module basename, original name) for
        #: ``from .transport import _HUB_LOCK`` style imports
        self.import_origin: Dict[str, Tuple[str, str]] = {}
        self._collect()

    def _leaf_at(self, line: int) -> bool:
        idx = line - 1
        return (0 <= idx < len(self.lines)
                and bool(_LEAF_RE.search(self.lines[idx])))

    def _collect(self) -> None:
        # imports anywhere (this codebase defers many imports into
        # function bodies to break import cycles)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(node)
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[t.id] = LockSite(
                                f"{self.display}:{t.id}", kind,
                                self.display, node.lineno,
                                self._leaf_at(node.lineno))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.funcs[node.name] = _Fn(node, self.display, None)
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)

    def _collect_import(self, node) -> None:
        if isinstance(node, ast.ImportFrom):
            modbase = (node.module or "").split(".")[-1]
            for a in node.names:
                self.import_mods[a.asname or a.name] = a.name
                if modbase:
                    self.import_origin[a.asname or a.name] = \
                        (modbase, a.name)
        else:
            for a in node.names:
                self.import_mods[a.asname or a.name] = \
                    a.name.split(".")[-1]

    def _collect_class(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        cls = _Cls(node.name, self.display, bases)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls.methods[item.name] = _Fn(item, self.display,
                                             node.name)
                self._collect_self_assigns(cls, item)
            elif isinstance(item, ast.AnnAssign) \
                    and isinstance(item.target, ast.Name):
                t = _ann_name(item.annotation)
                if t:
                    cls.attr_types.setdefault(item.target.id, t)
            elif isinstance(item, ast.Assign):
                # class-level lock: _REG_LOCK = threading.Lock()
                kind = _lock_ctor_kind(item.value)
                if kind:
                    for t in item.targets:
                        if isinstance(t, ast.Name):
                            cls.lock_attrs[t.id] = LockSite(
                                f"{node.name}.{t.id}", kind,
                                self.display, item.lineno,
                                self._leaf_at(item.lineno))
        # Thread(target=self.m) entry points, anywhere in the class
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                fname = n.func.attr if isinstance(n.func, ast.Attribute) \
                    else (n.func.id if isinstance(n.func, ast.Name)
                          else "")
                if fname != "Thread":
                    continue
                for kw in n.keywords:
                    if kw.arg == "target" \
                            and isinstance(kw.value, ast.Attribute) \
                            and isinstance(kw.value.value, ast.Name) \
                            and kw.value.value.id == "self":
                        cls.thread_targets.add(kw.value.attr)
        self.classes.append(cls)

    def _collect_self_assigns(self, cls: _Cls, fn_node) -> None:
        """Lock attrs + attr type hints from ``self.x = ...`` bodies and
        annotated __init__ params assigned straight onto self."""
        ann = {}
        if fn_node.name == "__init__":
            args = fn_node.args
            for a in args.args + args.kwonlyargs:
                t = _ann_name(a.annotation)
                if t:
                    ann[a.arg] = t
        for n in ast.walk(fn_node):
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                kind = _lock_ctor_kind(n.value)
                if kind:
                    cls.lock_attrs[t.attr] = LockSite(
                        f"{cls.name}.{t.attr}", kind, self.display,
                        n.lineno, self._leaf_at(n.lineno))
                    continue
                if isinstance(n.value, ast.Call) \
                        and isinstance(n.value.func, ast.Name):
                    cls.attr_types.setdefault(t.attr, n.value.func.id)
                elif isinstance(n.value, ast.Name) \
                        and n.value.id in ann:
                    cls.attr_types.setdefault(t.attr, ann[n.value.id])


# -- whole-package analysis --------------------------------------------------


class _Held:
    __slots__ = ("key", "text", "where", "leaf")

    def __init__(self, key, text, where, leaf):
        self.key = key
        self.text = text    # source text of the with-expr (exemptions)
        self.where = where  # "display:Lline (qual)"
        self.leaf = leaf


class _Package:
    def __init__(self, files: Dict[str, _File]):
        self.files = files
        self.graph = LockGraph()
        self.diags: List[Diagnostic] = []
        self.classes: Dict[str, _Cls] = {}
        self.mods: Dict[str, _File] = {}  # module basename -> file
        for f in files.values():
            base = os.path.basename(f.display)[:-3]
            self.mods.setdefault(base, f)
            for c in f.classes:
                self.classes.setdefault(c.name, c)
        self._summaries: Dict[int, Optional[dict]] = {}
        self._emitted: Set[tuple] = set()

    # -- emit ---------------------------------------------------------------

    def _emit(self, code: str, display: str, line: int, message: str,
              hint: Optional[str] = None) -> None:
        key = (code, display, line, message)
        if key in self._emitted:
            return
        self._emitted.add(key)
        f = self.files.get(display)
        if f is not None and f.suppress.active(code, line):
            return
        self.diags.append(Diagnostic.make(
            code, message, element=display, pad=f"L{line}", hint=hint))

    # -- resolution ---------------------------------------------------------

    def _mro(self, cls_name: str) -> List[_Cls]:
        out, todo, seen = [], [cls_name], set()
        while todo:
            name = todo.pop(0)
            if name in seen:
                continue
            seen.add(name)
            c = self.classes.get(name)
            if c is None:
                continue
            out.append(c)
            todo += c.bases
        return out

    def _find_method(self, cls_name: str, meth: str) -> Optional[_Fn]:
        for c in self._mro(cls_name):
            if meth in c.methods:
                return c.methods[meth]
        return None

    def _lock_attr_site(self, cls_name: str, attr: str
                        ) -> Optional[LockSite]:
        for c in self._mro(cls_name):
            if attr in c.lock_attrs:
                return c.lock_attrs[attr]
        return None

    def _attr_type(self, cls_name: str, attr: str) -> Optional[str]:
        for c in self._mro(cls_name):
            t = c.attr_types.get(attr)
            if t and t in self.classes:
                return t
        # name-match fallback: self.watch -> class Watch,
        # self.registry -> class MetricsRegistry
        stripped = attr.lstrip("_").lower()
        if len(stripped) >= 4:
            for name in self.classes:
                low = name.lower()
                if low == stripped or low.endswith(stripped):
                    return name
        return None

    def _infer_type(self, expr: ast.expr, fn: _Fn) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls"):
                return fn.cls
            if expr.id in self.classes:
                return expr.id
            # parameter annotations
            args = fn.node.args
            for a in args.args + args.kwonlyargs:
                if a.arg == expr.id:
                    t = _ann_name(a.annotation)
                    if t and t in self.classes:
                        return t
            return None
        if isinstance(expr, ast.Attribute):
            base_t = self._infer_type(expr.value, fn)
            if base_t:
                return self._attr_type(base_t, expr.attr)
        return None

    def _resolve_lock(self, expr: ast.expr, fn: _Fn
                      ) -> Optional[LockSite]:
        """Map a with-item context expression to a LockSite key, or
        None when the expression is not lock-like."""
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Name):
            f = self.files[fn.display]
            if expr.id in f.module_locks:
                return f.module_locks[expr.id]
            # a module lock imported from a sibling module by name
            origin = f.import_origin.get(expr.id)
            if origin is not None and origin[0] in self.mods \
                    and origin[1] in self.mods[origin[0]].module_locks:
                return self.mods[origin[0]].module_locks[origin[1]]
            if not _lockish(expr.id):
                return None
            return self._implicit(f"{fn.display}:{expr.id}",
                                  fn.display, expr.lineno)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            # module-attribute lock: _transport._HUB_LOCK
            if isinstance(expr.value, ast.Name):
                f = self.files[fn.display]
                mod = f.import_mods.get(expr.value.id)
                if mod in self.mods \
                        and attr in self.mods[mod].module_locks:
                    return self.mods[mod].module_locks[attr]
            rtype = self._infer_type(expr.value, fn)
            if rtype:
                site = self._lock_attr_site(rtype, attr)
                if site is not None:
                    return site
                if _lockish(attr):
                    return self._implicit(f"{rtype}.{attr}", fn.display,
                                          expr.lineno)
                return None
            if not _lockish(attr):
                return None
            # unique-attr heuristic: exactly one class in the package
            # declares a lock with this attr name (e.g. _alock)
            owners = [c for c in self.classes.values()
                      if attr in c.lock_attrs]
            if len(owners) == 1:
                return owners[0].lock_attrs[attr]
            return self._implicit(
                f"{fn.display}:{_unparse(expr)}", fn.display,
                expr.lineno)
        text = _unparse(expr)
        if text and _lockish(text):
            return self._implicit(f"{fn.display}:{text}", fn.display,
                                  expr.lineno)
        return None

    def _implicit(self, key: str, display: str, line: int) -> LockSite:
        site = self.graph.nodes.get(key)
        if site is None:
            site = LockSite(key, "?", display, line)
        return site

    def _resolve_call(self, call: ast.Call, fn: _Fn) -> Optional[_Fn]:
        f = call.func
        if isinstance(f, ast.Name):
            file = self.files[fn.display]
            if f.id in file.funcs:
                return file.funcs[f.id]
            origin = file.import_origin.get(f.id)
            if origin is not None and origin[0] in self.mods:
                return self.mods[origin[0]].funcs.get(origin[1])
            return None
        if not isinstance(f, ast.Attribute):
            return None
        if isinstance(f.value, ast.Name):
            # module alias call: _watch.maybe_start_from_env()
            file = self.files[fn.display]
            mod = file.import_mods.get(f.value.id)
            if mod in self.mods and f.attr in self.mods[mod].funcs:
                return self.mods[mod].funcs[f.attr]
        rtype = self._infer_type(f.value, fn)
        if rtype:
            return self._find_method(rtype, f.attr)
        return None

    # -- summaries (transitive lock/block behaviour per function) -----------

    def summary(self, fn: _Fn, depth: int = 0) -> dict:
        """``{"acquired": {key: [path lines]},
        "blocking": [(desc, path lines)]}`` — everything ``fn`` can do
        lock-wise, following package calls."""
        fid = id(fn.node)
        cached = self._summaries.get(fid)
        if cached is not None:
            return cached
        if fid in self._summaries or depth > _MAX_DEPTH:
            return {"acquired": {}, "blocking": []}  # recursion guard
        self._summaries[fid] = None  # in progress
        summ = {"acquired": {}, "blocking": []}
        self._walk(fn, fn.node.body, [], summ, depth, emit=False)
        self._summaries[fid] = summ
        return summ

    # -- the walk ------------------------------------------------------------

    def _where(self, fn: _Fn, line: int) -> str:
        return f"{fn.display}:L{line} ({fn.qual})"

    def _walk(self, fn: _Fn, body: Sequence[ast.stmt],
              held: List[_Held], summ: Optional[dict], depth: int,
              emit: bool = True) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run later; locks not held then
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = list(held)
                for item in stmt.items:
                    site = self._resolve_lock(item.context_expr, fn)
                    if site is None:
                        continue
                    self._on_acquire(fn, item.context_expr.lineno, site,
                                     acquired, summ, emit)
                    acquired = acquired + [_Held(
                        site.key, _unparse(item.context_expr),
                        self._where(fn, item.context_expr.lineno),
                        site.leaf)]
                self._walk(fn, stmt.body, acquired, summ, depth, emit)
                continue
            for expr in _stmt_exprs(stmt):
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call):
                        self._on_call(fn, node, held, summ, depth, emit)
            for key in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, key, None)
                if sub:
                    self._walk(fn, sub, held, summ, depth, emit)
            for h in getattr(stmt, "handlers", None) or []:
                self._walk(fn, h.body, held, summ, depth, emit)

    def _on_acquire(self, fn: _Fn, line: int, site: LockSite,
                    held: List[_Held], summ: Optional[dict],
                    emit: bool) -> None:
        self.graph.node(site)
        where = self._where(fn, line)
        if summ is not None:
            summ["acquired"].setdefault(
                site.key, [f"acquires {site.key} at {where}"])
        for h in held:
            if h.key == site.key:
                continue
            if emit:
                self.graph.edge(h.key, site.key, [
                    f"holds {h.key} since {h.where}",
                    f"acquires {site.key} at {where}"])
            if h.leaf and emit:
                self._emit(
                    "NNS604", fn.display, line,
                    f"{fn.qual} acquires {site.key} while holding the "
                    f"declared leaf lock {h.key} (held since "
                    f"{h.where}) — leaf locks promise to never nest",
                    hint="release the leaf lock first, or drop the "
                         "'# nns-lock: leaf' declaration if nesting "
                         "is intended")

    def _on_call(self, fn: _Fn, call: ast.Call, held: List[_Held],
                 summ: Optional[dict], depth: int, emit: bool) -> None:
        line = call.lineno
        desc = self._blocking_desc(call, held)
        if desc is not None:
            if summ is not None:
                summ["blocking"].append(
                    (desc, [f"blocks in {desc} at "
                            f"{self._where(fn, line)}"]))
            if held and emit:
                self._emit_hold_and_block(fn, line, desc, held, [])
        callee = self._resolve_call(call, fn)
        if callee is None or callee.node is fn.node:
            return
        sub = self.summary(callee, depth + 1)
        hop = f"calls {callee.qual}() at {self._where(fn, line)}"
        if summ is not None:
            for key, path in sub["acquired"].items():
                summ["acquired"].setdefault(key, [hop] + path)
            for bdesc, bpath in sub["blocking"]:
                summ["blocking"].append((bdesc, [hop] + bpath))
        if not held:
            return
        for key, path in sub["acquired"].items():
            for h in held:
                if h.key == key:
                    continue
                if emit:
                    self.graph.edge(h.key, key, [
                        f"holds {h.key} since {h.where}", hop] + path)
                if h.leaf and emit:
                    self._emit(
                        "NNS604", fn.display, line,
                        f"{fn.qual} calls {callee.qual}() — which "
                        f"acquires {key} — while holding the declared "
                        f"leaf lock {h.key} (held since {h.where})",
                        hint="\n".join([hop] + path))
        if emit:
            for bdesc, bpath in sub["blocking"]:
                self._emit_hold_and_block(fn, line, bdesc, held,
                                          [hop] + bpath)

    def _emit_hold_and_block(self, fn: _Fn, line: int, desc: str,
                             held: List[_Held],
                             via: List[str]) -> None:
        locks = "/".join(h.key for h in held)
        hint = "move the blocking call outside the lock (snapshot " \
               "state under the lock, act on it after release)"
        if via:
            hint = "\n".join(via) + "\n" + hint
        self._emit(
            "NNS602", fn.display, line,
            f"{fn.qual} makes the blocking call {desc} while holding "
            f"{locks} (hold-and-block)", hint=hint)

    def _blocking_desc(self, call: ast.Call, held: List[_Held]
                       ) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        if isinstance(f.value, ast.Constant):
            return None  # "sep".join(...) string op
        recv = _unparse(f.value)
        if f.attr in _WAIT_ATTRS:
            if any(h.text == recv for h in held):
                return None  # Condition.wait releases the held lock
            return f"{recv}.{f.attr}()"
        if f.attr == "join" and recv in _PATH_MODULES:
            return None  # os.path.join: string op, not thread join
        if f.attr in _BLOCK_ATTRS:
            return f"{recv}.{f.attr}()"
        if f.attr == "snapshot" and _REGISTRYISH.search(recv):
            return f"{recv}.snapshot() (full registry scrape)"
        return None

    # -- passes --------------------------------------------------------------

    def run(self) -> None:
        for f in self.files.values():
            for fn in f.funcs.values():
                self._walk(fn, fn.node.body, [], None, 0)
            for c in f.classes:
                for fn in c.methods.values():
                    self._walk(fn, fn.node.body, [], None, 0)
        self._report_cycles()
        for f in self.files.values():
            for c in f.classes:
                self._check_shared_state(c)

    def _report_cycles(self) -> None:
        for cyc in self.graph.cycles():
            arrows = " -> ".join(cyc)
            hint_lines: List[str] = []
            for a, b in zip(cyc, cyc[1:]):
                e = self.graph.edges.get((a, b))
                if e is None:
                    continue
                hint_lines.append(f"{a} -> {b}:")
                hint_lines += [f"  {step}" for step in e["path"]]
            first = self.graph.edges.get((cyc[0], cyc[1]))
            display, line = "", 0
            if first is not None:
                m = re.search(r"at ([^\s]+):L(\d+)", first["path"][-1])
                if m:
                    display, line = m.group(1), int(m.group(2))
            self._emit(
                "NNS601", display or cyc[0], line,
                f"lock-order cycle {arrows}: two paths take these "
                f"locks in opposite orders — a potential deadlock",
                hint="\n".join(hint_lines))

    def _check_shared_state(self, cls: _Cls) -> None:
        if not cls.thread_targets:
            return
        writes: Dict[str, List[Tuple[str, int, bool, bool]]] = {}

        def record(fn: _Fn, body, held: bool):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                now_held = held or (
                    isinstance(stmt, (ast.With, ast.AsyncWith))
                    and any(self._resolve_lock(i.context_expr, fn)
                            for i in stmt.items))
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    targets = [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        if isinstance(stmt, ast.Assign) \
                                and _sync_ctor(stmt.value):
                            continue  # (re)binding a sync primitive
                        writes.setdefault(t.attr, []).append(
                            (fn.node.name, stmt.lineno, now_held,
                             fn.node.name in cls.thread_targets))
                for key in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, key, None)
                    if sub:
                        record(fn, sub, now_held)
                for h in getattr(stmt, "handlers", None) or []:
                    record(fn, h.body, now_held)

        for name, fn in cls.methods.items():
            if name == "__init__":
                continue
            record(fn, fn.node.body, False)
        for attr, sites in writes.items():
            from_thread = [s for s in sites if s[3]]
            from_public = [s for s in sites
                           if not s[3] and not s[0].startswith("_")]
            if not from_thread or not from_public:
                continue
            unguarded = [s for s in from_thread + from_public
                         if not s[2]]
            if not unguarded:
                continue
            meth, line = unguarded[0][0], unguarded[0][1]
            others = sorted({f"{s[0]} (L{s[1]})"
                             for s in from_thread + from_public
                             if (s[0], s[1]) != (meth, line)})
            self._emit(
                "NNS603", cls.display, line,
                f"{cls.name}.{attr} is written by the thread entry "
                f"point(s) {sorted(set(s[0] for s in from_thread))} "
                f"and the public method(s) "
                f"{sorted(set(s[0] for s in from_public))} with no "
                f"guarding lock at {meth} (L{line})",
                hint="guard every cross-thread write with one lock, "
                     "or confine the field to a single thread; "
                     "other write sites: " + ", ".join(others))


# -- public API --------------------------------------------------------------


def _stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
    out: List[ast.expr] = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out += [v for v in value if isinstance(v, ast.expr)]
    return out


def analyze_sources(sources: Dict[str, str]
                    ) -> Tuple[List[Diagnostic], LockGraph]:
    """Run the NNS6xx pass over ``{display_path: source}``.  Files that
    do not parse yield an NNS403-style parse diagnostic and are skipped
    (same convention as :func:`.codelint.lint_package`)."""
    files: Dict[str, _File] = {}
    diags: List[Diagnostic] = []
    for display, source in sorted(sources.items()):
        try:
            files[display] = _File(display, source)
        except SyntaxError as e:
            diags.append(Diagnostic.make(
                "NNS403", f"{display}: does not parse: {e}",
                element=display, pad=f"L{e.lineno or 0}"))
    pkg = _Package(files)
    pkg.run()
    return sort_diagnostics(diags + pkg.diags), pkg.graph


def lint_concurrency_source(source: str, path: str = "<string>"
                            ) -> List[Diagnostic]:
    """Single-source convenience (tests, snippets)."""
    return analyze_sources({path: source})[0]


def analyze_package_concurrency(pkg_root: str
                                ) -> Tuple[List[Diagnostic], LockGraph]:
    """The ``--concurrency`` entry point: NNS6xx over every module of a
    package checkout, lock graph included."""
    pkg_root = os.path.abspath(pkg_root)
    base = os.path.dirname(pkg_root)
    sources: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "native")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            display = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                sources[display] = f.read()
    return analyze_sources(sources)

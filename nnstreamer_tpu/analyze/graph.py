"""Pass 1 — graph verifier (NNS1xx).

Checks the *structure* of an assembled (not started) Pipeline: dangling
pads, zero-sink/zero-source graphs, cycles, and elements no source can
ever feed.  Runs no threads and negotiates nothing — parity with what
``gst-validate`` can prove from a launch line alone.

``fragment=True`` analyzes a pipeline snippet (doc examples starting with
``... !``): structural findings that a fragment legitimately lacks
(source/sink/unlinked edge pads) downgrade to info.
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..runtime.element import Element, SourceElement
from ..runtime.pipeline import Pipeline
from .diagnostics import Diagnostic, Severity


def _downgrade(fragment: bool):
    return Severity.INFO if fragment else None


def verify_graph(pipe: Pipeline, fragment: bool = False) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    elements = list(pipe.elements.values())
    if not elements:
        diags.append(Diagnostic.make(
            "NNS107", "pipeline is empty", element=pipe.name))
        return diags

    sources = [e for e in elements if isinstance(e, SourceElement)]
    sinks = [e for e in elements if e.sinkpads and not e.srcpads]
    if not sources:
        diags.append(Diagnostic.make(
            "NNS107", "pipeline has no source element — nothing will ever "
            "produce data", element=pipe.name,
            hint="add a source (appsrc, device_src, filesrc, ...) or link "
                 "this fragment downstream of one",
            severity=_downgrade(fragment)))
    if not sinks:
        diags.append(Diagnostic.make(
            "NNS106", "pipeline has no sink element — EOS tracking and "
            "wait_eos() will never complete", element=pipe.name,
            hint="terminate every branch in a sink (tensor_sink, appsink, "
                 "filesink, ...)", severity=_downgrade(fragment)))

    for e in elements:
        for p in e.sinkpads:
            if p.peer is None:
                diags.append(Diagnostic.make(
                    "NNS101", f"sink pad {e.name}.{p.name} is not linked — "
                    f"Pipeline.start() will refuse this graph",
                    element=e.name, pad=p.name,
                    hint="link an upstream element into this pad or remove "
                         "the element", severity=_downgrade(fragment)))
        for p in e.srcpads:
            if p.peer is None:
                diags.append(Diagnostic.make(
                    "NNS102", f"src pad {e.name}.{p.name} is not linked — "
                    f"buffers pushed there are silently dropped",
                    element=e.name, pad=p.name,
                    hint="link the pad downstream or drop it (request pads "
                         "only exist because something asked for them)",
                    severity=_downgrade(fragment)))

    diags += _find_cycles(elements)
    diags += _find_unreachable(elements, sources, fragment)
    diags += _batching_checks(elements, fragment)
    diags += _mesh_checks(elements)
    diags += _pool_mesh_checks(elements)
    diags += _serving_checks(elements)
    diags += _lifecycle_checks(elements)
    diags += _edge_checks(elements)
    diags += _obs_checks(elements)
    diags += _dataflow_checks(elements)
    diags += _fusion_checks(elements)
    diags += _stage_checks(elements)
    return diags


def _adjacency(elements: List[Element]) -> Dict[str, List[str]]:
    adj: Dict[str, List[str]] = {e.name: [] for e in elements}
    for e in elements:
        for sp in e.srcpads:
            if sp.peer is not None:
                adj[e.name].append(sp.peer.element.name)
    return adj


def _find_cycles(elements: List[Element]) -> List[Diagnostic]:
    """Iterative DFS three-color cycle detection; reports each cycle once
    with the element path."""
    adj = _adjacency(elements)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    diags: List[Diagnostic] = []
    reported: Set[frozenset] = set()
    for root in adj:
        if color[root] != WHITE:
            continue
        stack = [(root, iter(adj[root]))]
        color[root] = GREY
        path = [root]
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color[nxt] == GREY:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in reported:
                        reported.add(key)
                        diags.append(Diagnostic.make(
                            "NNS104",
                            "cycle in the pipeline graph: "
                            + " -> ".join(cyc),
                            element=nxt,
                            hint="pipelines are DAGs; feed state back "
                                 "through tensor_reposink/tensor_reposrc "
                                 "slots instead of pad links"))
                elif color[nxt] == WHITE:
                    color[nxt] = GREY
                    stack.append((nxt, iter(adj[nxt])))
                    path.append(nxt)
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                stack.pop()
                path.pop()
    return diags


def _find_unreachable(elements: List[Element],
                      sources: List[Element],
                      fragment: bool) -> List[Diagnostic]:
    """BFS downstream from every source; anything never visited can never
    see a buffer."""
    if fragment or not sources:
        # fragments have no sources by construction; a fully source-less
        # graph is already NNS107 — flagging every element adds noise
        return []
    adj = _adjacency(elements)
    seen: Set[str] = set()
    frontier = [s.name for s in sources]
    seen.update(frontier)
    while frontier:
        nxt: List[str] = []
        for n in frontier:
            for m in adj[n]:
                if m not in seen:
                    seen.add(m)
                    nxt.append(m)
        frontier = nxt
    diags: List[Diagnostic] = []
    for e in elements:
        if e.name not in seen:
            diags.append(Diagnostic.make(
                "NNS105", f"element {e.name} is unreachable: no source "
                f"element feeds it", element=e.name,
                hint="link it downstream of a source or remove it"))
    return diags


def _int_prop(e: Element, name: str, default: int = 0) -> int:
    try:
        return int(getattr(e, name, default) or default)
    except (TypeError, ValueError):
        return default


def _has_upstream_queue(e: Element) -> bool:
    """Whether any ``queue`` sits in the upstream closure of ``e``."""
    seen = {e.name}
    frontier: List[Element] = [e]
    while frontier:
        cur = frontier.pop()
        for p in cur.sinkpads:
            if p.peer is None:
                continue
            up = p.peer.element
            if up.name in seen:
                continue
            seen.add(up.name)
            if getattr(up, "FACTORY", "") == "queue":
                return True
            frontier.append(up)
    return False


def _batching_checks(elements: List[Element],
                     fragment: bool) -> List[Diagnostic]:
    """NNS5xx: micro-batching topology (runtime/batching.py).  A
    ``tensor_filter batch>1`` only coalesces when a ``queue`` decouples
    it from its producer (the thread boundary lets buffers pile into the
    window; chained directly, each producer push waits out the deadline
    instead), and ``latency=1`` forces every dispatch synchronous, so
    windows never hold more than the one frame in flight.  NNS505 is the
    dual: ``latency=1`` *behind* a queue reports a number the queue's
    buffering makes misleading."""
    diags: List[Diagnostic] = []
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_filter":
            continue
        batch = _int_prop(e, "batch", 1)
        latency = _int_prop(e, "latency", 0)
        if batch <= 1 and latency != 1:
            continue
        has_queue = _has_upstream_queue(e)
        if batch > 1 and latency == 1:
            diags.append(Diagnostic.make(
                "NNS502",
                f"{e.name}: batch={batch} with latency=1 — synchronous "
                f"per-invoke measurement blocks the stream on every "
                f"dispatch, so the coalescing window never holds more "
                f"than the frame being measured",
                element=e.name,
                hint="drop latency=1 (use the sampled stats) or batch=1 "
                     "for latency-calibration runs"))
        if batch > 1 and not has_queue:
            diags.append(Diagnostic.make(
                "NNS501",
                f"{e.name}: batch={batch} but no queue upstream — "
                f"without a thread boundary the producer hands one "
                f"buffer at a time, so every window closes on the "
                f"batch-timeout-ms deadline with one frame: all added "
                f"latency, no coalescing",
                element=e.name,
                hint="insert `queue !` in front of the filter (or drop "
                     "batch=)", severity=_downgrade(fragment)))
        if latency == 1 and has_queue:
            diags.append(Diagnostic.make(
                "NNS505",
                f"{e.name}: latency=1 measures only the synchronous "
                f"invoke, but an upstream queue parks buffers ahead of "
                f"this filter — a frame's end-to-end latency is invoke "
                f"time PLUS queue residency, which the reported number "
                f"cannot see",
                element=e.name,
                hint="for true per-frame latency attach the obs latency "
                     "tracer (Documentation/observability.md) — it "
                     "breaks the end-to-end time down per element, "
                     "queue residency included"))
    return diags


def _mesh_data_axis_size(mesh_spec: str, devices_prop: str):
    """Statically resolvable size of the mesh's data axis (the axis
    ``jax_xla`` batch-shards over: "data" when present, else the first
    axis), or None when it cannot be known at analysis time (a ``-1``
    wildcard with no explicit ``devices=`` subset)."""
    from ..parallel.mesh import MeshSpec

    try:
        spec = MeshSpec.parse(str(mesh_spec))
    except (TypeError, ValueError):
        return None  # unparseable mesh: the open itself will fail
    if not spec.axes:
        return None
    names = [n for n, _ in spec.axes]
    data = "data" if "data" in names else names[0]
    sizes = dict(spec.axes)
    size = sizes.get(data, -1)
    if size == -1:
        # wildcard: only resolvable when devices= pins the count and
        # every OTHER axis is fixed
        devs = str(devices_prop or "").strip()
        fixed = 1
        for name, s in spec.axes:
            if name != data:
                if s == -1:
                    return None
                fixed *= s
        if not devs:
            return None
        try:
            from ..parallel.mesh import parse_device_indices

            n_devs = len(parse_device_indices(devs, 1 << 30))
        except ValueError:
            return None
        return n_devs // fixed if fixed and n_devs % fixed == 0 else None
    return int(size)


def _mesh_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS509: mesh/sharded placement whose micro-batch cannot split
    evenly over the data axis.  ``invoke_batched`` only applies the
    batch-sharding constraint when the bucket divides the axis size —
    otherwise the window pads up (pad slots run the full computation on
    every dispatch) or replicates onto every chip.  The obs layer
    measures this at runtime (``nns_mesh_pad_slots_total``,
    ``nns_shard_imbalance``); this check catches it before anything
    runs."""
    diags: List[Diagnostic] = []
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_filter":
            continue
        if bool(getattr(e, "share_model", False)):
            continue  # pool-level windows: NNS512 owns those
        mesh_spec = str(getattr(e, "mesh", "") or "").strip()
        if not mesh_spec:
            continue
        size = _mesh_data_axis_size(mesh_spec,
                                    getattr(e, "devices", ""))
        if size is None or size <= 1:
            continue
        batch = _int_prop(e, "batch", 1)
        if batch <= 1:
            continue
        # the steady-state window dispatches at `batch` (a full window
        # never pads) plus any EXPLICIT bucket; the implicit
        # power-of-two ladder only serves deadline-closed partials and
        # would make every mesh+batch combination fire
        bad = sorted(b for b in _bucket_set(e) if b % size)
        if not bad:
            continue
        diags.append(Diagnostic.make(
            "NNS509",
            f"{e.name}: mesh={mesh_spec} shards the micro-batch over "
            f"{size} data-axis devices, but bucket(s) "
            f"{', '.join(map(str, bad))} are not divisible by {size} — "
            f"every such window pads up (pad slots run the full "
            f"computation) or replicates onto every chip: device time "
            f"burned on no frames, on every dispatch",
            element=e.name,
            hint=f"size batch/batch-buckets as multiples of {size} "
                 f"(the data-axis size) so every window splits evenly; "
                 f"the runtime counterpart is nns_mesh_pad_slots_total "
                 f"/ nns_shard_imbalance "
                 f"(Documentation/observability.md)"))
    return diags


def _bucket_set(e: Element) -> set:
    """The window sizes a filter's coalescer can dispatch at: its
    ``batch`` plus any EXPLICIT buckets (the implicit power-of-two
    ladder only serves deadline-closed partials — counting it would
    fire on every mesh+batch combination).  Empty set when the bucket
    spec is unparseable (start() reports that itself)."""
    batch = _int_prop(e, "batch", 1)
    buckets = {batch}
    for tok in str(getattr(e, "batch_buckets", "") or "").split(","):
        tok = tok.strip()
        if tok:
            try:
                buckets.add(int(tok))
            except ValueError:
                return set()
    return buckets


def _static_placement(e: Element):
    """Lint-time placement identity of a filter: parsed mesh axes (with
    ``-1`` wildcards kept — no device enumeration at lint time), the
    CANONICAL sharding-rules name (``dp``/``replicated`` are one rule
    set), and the devices subset.  None when the mesh is unparseable.
    Deliberately coarser than ``parallel.Placement.key()``: two
    spellings that MIGHT resolve equal (``data:-1`` vs ``data:8``)
    compare equal here only when provably so, so the conflict check
    below never flags a pair the runtime would happily join."""
    from ..parallel.mesh import MeshSpec
    from ..parallel.sharded import PARAM_RULES

    mesh_spec = str(getattr(e, "mesh", "") or "").strip()
    try:
        axes = MeshSpec.parse(mesh_spec).axes if mesh_spec else ()
    except (TypeError, ValueError):
        return None
    sharding = str(getattr(e, "sharding", "") or "").strip() \
        or "replicated"
    rules = PARAM_RULES.get(sharding)
    canonical = sorted(k for k, v in PARAM_RULES.items()
                       if v is rules)[0] if rules is not None else sharding
    devices = str(getattr(e, "devices", "") or "").strip()
    if devices:
        # canonicalize the index-subset spelling ("0-3" == "0,1,2,3")
        # the way the runtime does — a raw-string compare would flag a
        # conflict the pool never raises
        try:
            from ..parallel.mesh import parse_device_indices

            devices = parse_device_indices(devices, 1 << 30)
        except (TypeError, ValueError):
            pass  # unparseable: the open itself reports it
    return (axes, canonical, devices)


def _axes_compatible(a, b) -> bool:
    """Whether two parsed mesh-axes tuples COULD resolve to the same
    mesh: same names in order, each size pair equal or either a ``-1``
    wildcard (``data:-1`` vs ``data:8`` may well be the same placement
    at runtime — only a resolved count can tell)."""
    if len(a) != len(b):
        return False
    for (na, sa), (nb, sb) in zip(a, b):
        if na != nb:
            return False
        if sa != sb and -1 not in (sa, sb):
            return False
    return True


def _placements_conflict(placements: List[tuple]) -> bool:
    """True when SOME pair of static placements is provably
    irreconcilable — the conservative static face of the runtime's
    canonical-key comparison (which sees resolved device counts and
    never flags equivalent spellings)."""
    for i, (axes_a, rules_a, devs_a) in enumerate(placements):
        for axes_b, rules_b, devs_b in placements[i + 1:]:
            if rules_a != rules_b \
                    or not _axes_compatible(axes_a, axes_b):
                return True
            # devices subsets are provably different only when BOTH
            # are explicit and unequal: an omitted devices= lays the
            # mesh over the device prefix, which may well BE the
            # named subset ("mesh=data:4" == "devices=0-3" on most
            # hosts — the runtime joins them into one pool)
            if devs_a and devs_b and devs_a != devs_b:
                return True
    return False


def _pool_mesh_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS512: pool-level NNS509 for ``share-model=true`` filters.

    Sharing filters of one model form ONE serving pool with ONE
    cross-pipeline window (runtime/serving.py), so mesh divisibility is
    a property of the POOL: a window size not divisible by the data-axis
    size pads (or replicates) on EVERY coalesced window, burning device
    time for every sharer at once.  Also the static face of the
    runtime's PoolConflictError: sharers that declare provably different
    placements would not share at all — the pool refuses the second
    placement at start()."""
    diags: List[Diagnostic] = []
    pools: Dict[tuple, List[Element]] = {}
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_filter":
            continue
        if not bool(getattr(e, "share_model", False)):
            continue
        model = getattr(e, "model", None)
        if model is None:
            continue
        if not isinstance(model, str):
            # non-string models (callables, ModelDef) pool by object
            # identity at runtime — they still deserve the
            # divisibility check (the window pads regardless of how
            # the model was handed in)
            model = f"<{type(model).__name__}:{id(model):#x}>"
        elif not model:
            continue
        fw = str(getattr(e, "framework", "") or "auto")
        # mirror the runtime pool identity MINUS placement
        # (serving._key_base): filters differing in custom/IO-spec/
        # shared-key open DIFFERENT pools, so their placements can
        # never conflict — grouping by model alone would predict a
        # PoolConflictError that start() never raises
        pools.setdefault(
            (fw, model,
             str(getattr(e, "custom", "") or ""),
             str(getattr(e, "input", "") or ""),
             str(getattr(e, "inputtype", "") or ""),
             str(getattr(e, "output", "") or ""),
             str(getattr(e, "outputtype", "") or ""),
             str(getattr(e, "shared_tensor_filter_key", "") or "")),
            []).append(e)
    for (fw, model, *_rest), els in pools.items():
        placements = {}
        for el in els:
            p = _static_placement(el)
            if p is not None:
                placements.setdefault(p, []).append(el)
        if _placements_conflict(list(placements)):
            groups = "; ".join(
                f"{'/'.join(el.name for el in group)}: "
                f"mesh={str(getattr(group[0], 'mesh', '') or '')!r}"
                for group in placements.values())
            diags.append(Diagnostic.make(
                "NNS512",
                f"share-model filters of model {model!r} declare "
                f"conflicting placements ({groups}) — placement is "
                f"pool-level: the pool refuses the second placement "
                f"with a PoolConflictError at start()",
                element=els[0].name,
                hint="align mesh/sharding/devices across every sharer "
                     "of one model (equivalent spellings like data:-1 "
                     "vs data:8 join automatically; provably different "
                     "ones cannot share)"))
            continue  # divisibility against an ambiguous pool mesh
            # would double-report the same misconfiguration
        meshed = [el for el in els
                  if str(getattr(el, "mesh", "") or "").strip()]
        if not meshed:
            continue
        ref = meshed[0]
        size = _mesh_data_axis_size(
            str(getattr(ref, "mesh", "") or "").strip(),
            getattr(ref, "devices", ""))
        if size is None or size <= 1:
            continue
        # pool-level window settings: every sharer must agree (runtime
        # PoolConflictError) — lint the UNION of their declared buckets
        buckets: set = set()
        for el in els:
            buckets |= _bucket_set(el)
        buckets.discard(1)
        bad = sorted(b for b in buckets if b % size)
        if not bad:
            continue
        names = ", ".join(el.name for el in els)
        diags.append(Diagnostic.make(
            "NNS512",
            f"share-model pool of model {model!r} ({names}) shards its "
            f"coalesced window over {size} data-axis devices, but "
            f"pool window size(s) {', '.join(map(str, bad))} are not "
            f"divisible by {size} — EVERY cross-pipeline window pads "
            f"up (pad slots run the full computation) or replicates "
            f"onto every chip: device time burned on no frames, for "
            f"every sharer at once",
            element=ref.name,
            hint=f"size the pool's batch/batch-buckets as multiples of "
                 f"{size} (the data-axis size); the runtime counterpart "
                 f"is nns_pool_pad_frac / nns_pool_shard_imbalance "
                 f"(Documentation/serving.md \"Mesh-native pools\")"))
    return diags


def _edge_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS506: distributed-tracing clock hygiene.  A traced
    ``tensor_query_client`` on a cross-host link (``connect-type`` tcp
    or hybrid) aligns the server's spans using the in-band 4-timestamp
    estimate, which assumes symmetric network delay; with no
    ``ntp-servers=`` configured there is no wall-clock cross-check, so
    a persistently asymmetric path (e.g. duplex-imbalanced WAN) skews
    the placement of remote spans silently."""
    diags: List[Diagnostic] = []
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_query_client":
            continue
        if str(getattr(e, "connect_type", "tcp")) != "inproc":
            # NNS507: a cross-host query link with the timeout or the
            # max-request bound disabled has NO defense against a dead
            # or stalled server — in-flight entries (and the buffers
            # they pin) grow without bound, and EOS can never drain.
            # (_int_prop's `or default` would fold the EXPLICIT 0 this
            # check is about back into the default — read directly.)
            try:
                timeout = int(getattr(e, "timeout", 10000))
            except (TypeError, ValueError):
                timeout = 10000
            try:
                maxreq = int(getattr(e, "max_request", 8))
            except (TypeError, ValueError):
                maxreq = 8
            if timeout <= 0 or maxreq <= 0:
                off = " and ".join(
                    ["timeout=0"] * (timeout <= 0)
                    + ["max-request=0"] * (maxreq <= 0))
                diags.append(Diagnostic.make(
                    "NNS507",
                    f"{e.name}: cross-host query link with {off} — "
                    f"against a dead or stalled server, in-flight "
                    f"requests (and the frames they pin) grow without "
                    f"bound and nothing ever times out",
                    element=e.name,
                    hint="set timeout= (ms) so lost replies surface as "
                         "timeouts, and max-request= so a slow server "
                         "sheds input instead of queueing unboundedly "
                         "(Documentation/robustness.md)"))
        if not bool(getattr(e, "trace", True)):
            continue
        if str(getattr(e, "connect_type", "tcp")) == "inproc":
            continue  # same process, same clock: nothing to align
        if str(getattr(e, "ntp_servers", "") or "").strip():
            continue
        diags.append(Diagnostic.make(
            "NNS506",
            f"{e.name}: trace propagation on a cross-host link without "
            f"NTP sync — remote spans are placed via the in-band "
            f"round-trip estimate only, which assumes the network path "
            f"is symmetric",
            element=e.name,
            hint="set ntp-servers=host[:port],... on the client (and "
                 "server host) for a wall-clock cross-check, or "
                 "trace=false to stop propagating trace contexts "
                 "(Documentation/observability.md)"))
    return diags


def _obs_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS508: observability props on a pipeline running with the
    global obs kill switch set (``NNS_TPU_OBS_DISABLE``).  Under the
    switch no blocking stat sample is ever taken and no tracer can
    attach, so ``stat-sample-interval-ms``, ``latency=1``,
    ``latency-report`` and query-client ``trace`` propagation all
    silently no-op — the user asked for numbers nobody will produce."""
    from ..obs import hooks as obs_hooks

    if not obs_hooks.obs_disabled():
        return []
    diags: List[Diagnostic] = []
    for e in elements:
        props: List[str] = []
        if getattr(e, "stat_sample_interval_ms", None) is not None:
            props.append("stat-sample-interval-ms")
        if _int_prop(e, "latency", 0) == 1:
            props.append("latency=1")
        if bool(getattr(e, "latency_report", False)):
            props.append("latency-report")
        if getattr(e, "FACTORY", "") == "tensor_query_client" \
                and bool(getattr(e, "trace", False)):
            props.append("trace")
        if not props:
            continue
        diags.append(Diagnostic.make(
            "NNS508",
            f"{e.name}: {', '.join(props)} set, but observability is "
            f"globally disabled (NNS_TPU_OBS_DISABLE) — no latency "
            f"sample will ever be taken and no trace context will "
            f"propagate; the prop(s) silently no-op",
            element=e.name,
            hint="unset NNS_TPU_OBS_DISABLE to get the numbers these "
                 "props ask for, or drop the props "
                 "(Documentation/observability.md)"))
    return diags


#: the version-labelled metric families the model lifecycle exports —
#: a canary= declaration whose active watch rules bind NONE of these
#: has no judge: promotion/rollback would never trigger (NNS513)
MODEL_SERIES = frozenset({
    "nns_model_version_invokes_total",
    "nns_model_version_frames_total",
    "nns_model_version_errors_total",
    "nns_model_version_latency_us",
    "nns_model_version_state",
    "nns_model_canary_streams",
    "nns_model_canary_latency_us",
    "nns_model_baseline_latency_us",
    "nns_model_canary_errors_total",
    "nns_model_canary_frames_total",
})


def _supports_reload(e: Element) -> bool:
    """Whether this filter's framework can actually hot-reload: it
    implements ``prepare_swap`` (the lifecycle's double-buffered
    path) or overrides the RELOAD_MODEL event handler."""
    fw = str(getattr(e, "framework", "") or "auto")
    model = getattr(e, "model", None)
    try:
        from ..filters.api import FilterSubplugin
        from ..filters.registry import detect_framework, find_filter

        if fw in ("", "auto"):
            fw = detect_framework(model)
        cls = find_filter(fw)
    except (ValueError, KeyError):
        return True  # unknown framework: the open itself will complain
    return callable(getattr(cls, "prepare_swap", None)) \
        or cls.handle_event is not FilterSubplugin.handle_event


def _lifecycle_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS513 (element faces): canary grammar / canary without
    share-model, is-updatable on a framework without reload support,
    and a misconfigured persistent compile-cache directory.  The
    canary-without-watch-rule face needs the active rule set and runs
    in the CLI (``canary_watch_checks``).  Also the element face of
    NNS517: ``tenant=`` on a filter that never dispatches through a
    shared pool."""
    import os

    diags: List[Diagnostic] = []
    filters = [e for e in elements
               if getattr(e, "FACTORY", "") == "tensor_filter"]
    for e in filters:
        canary = str(getattr(e, "canary", "") or "").strip()
        if canary:
            from ..runtime.lifecycle import LifecycleError, parse_canary

            try:
                parse_canary(canary)
            except LifecycleError as err:
                diags.append(Diagnostic.make(
                    "NNS513", f"{e.name}: {err}", element=e.name,
                    hint="canary grammar: '<version>:1/N' or '1/N' "
                         "(Documentation/lifecycle.md)"))
            else:
                if not bool(getattr(e, "share_model", False)):
                    diags.append(Diagnostic.make(
                        "NNS513",
                        f"{e.name}: canary={canary!r} without "
                        f"share-model=true — canarying routes 1-in-N "
                        f"STREAMS of a shared pool; a private filter "
                        f"has exactly one stream and nothing to split",
                        element=e.name,
                        hint="set share-model=true (the canary split "
                             "is pool-level) or drop canary="))
        tenant = str(getattr(e, "tenant", "") or "").strip()
        if tenant and not bool(getattr(e, "share_model", False)):
            diags.append(Diagnostic.make(
                "NNS517",
                f"{e.name}: tenant={tenant!r} without share-model="
                f"true — tenant attribution splits the SHARED pool's "
                f"device-seconds across the streams parked in each "
                f"window; a private filter never dispatches through "
                f"a pool, so nothing is ever billed to the tenant",
                element=e.name,
                hint="set share-model=true (attribution is pool-"
                     "level) or drop tenant= "
                     "(Documentation/observability.md)"))
        if bool(getattr(e, "is_updatable", False)) \
                and not _supports_reload(e):
            fw = str(getattr(e, "framework", "") or "auto")
            diags.append(Diagnostic.make(
                "NNS513",
                f"{e.name}: is-updatable=true, but framework {fw!r} "
                f"implements neither prepare_swap nor a RELOAD_MODEL "
                f"handler — a reload event will raise instead of "
                f"swapping",
                element=e.name,
                hint="drop is-updatable, or use a framework with "
                     "reload support (jax-xla)"))
    cache_dir = os.environ.get("NNS_TPU_COMPILE_CACHE_DIR", "").strip()
    if filters and cache_dir and (
            not os.path.isdir(cache_dir)
            or not os.access(cache_dir, os.W_OK)):
        diags.append(Diagnostic.make(
            "NNS513",
            f"NNS_TPU_COMPILE_CACHE_DIR={cache_dir!r} is not a "
            f"writable directory — the persistent AOT compile cache "
            f"silently disables and every fresh process pays the full "
            f"XLA trace+build again",
            element=filters[0].name,
            hint="create the directory (writable) or unset "
                 "NNS_TPU_COMPILE_CACHE_DIR "
                 "(Documentation/lifecycle.md)"))
    return diags


def canary_watch_checks(pipelines, rules) -> List[Diagnostic]:
    """NNS513 (rules face): a ``canary=`` declaration whose ACTIVE
    watch rule set binds none of the version-labelled series — the
    canary would route traffic forever with no judge to promote or
    roll it back.  ``rules`` is the same-invocation rule set
    (--watch-rules file, else $NNS_TPU_WATCH_RULES, else the default
    pack — which binds none of them)."""
    canary_els = []
    for pipe in pipelines:
        for e in pipe.elements.values():
            if getattr(e, "FACTORY", "") == "tensor_filter" \
                    and str(getattr(e, "canary", "") or "").strip() \
                    and bool(getattr(e, "share_model", False)):
                canary_els.append(e)
    if not canary_els:
        return []
    bound = any(r.metric in MODEL_SERIES
                or getattr(r, "per", "") in MODEL_SERIES
                for r in rules)
    if bound:
        return []
    return [Diagnostic.make(
        "NNS513",
        f"{e.name}: canary={str(getattr(e, 'canary', '')).strip()!r} "
        f"declared, but no active watch rule binds any "
        f"version-labelled series (nns_model_canary_latency_us, "
        f"nns_model_canary_errors_total, ...) — nothing will ever "
        f"judge the canary, so promotion/rollback never triggers",
        element=e.name,
        hint="add a comparator rule pair (canary latency vs baseline "
             "via per=, canary error rate) and promote/rollback "
             "playbooks (Documentation/lifecycle.md)")
        for e in canary_els]


#: frameworks whose sub-plugin instances carry host-side per-stream
#: state (user callables / script objects): sharing ONE instance across
#: pipelines via the serving pool is unsafe unless the user code is
#: explicitly reentrant
_STATEFUL_FRAMEWORKS = frozenset({"custom", "custom-easy", "python3"})

#: residency-transparent elements: they forward whatever residency
#: their input has (queue/tee pass references; mux/merge/demux/split
#: fan in/out on device whenever the inputs are device-resident) — the
#: NNS514 sandwich walk looks THROUGH them
_RESIDENCY_TRANSPARENT = frozenset({
    "queue", "tee", "identity", "join", "tensor_mux", "tensor_merge",
    "tensor_demux", "tensor_split"})

#: elements that compute on host, full stop: their chain reads every
#: input tensor on host and emits host arrays — between two device
#: stages they are a residency FENCE (one d2h + one h2d per frame)
_HOST_ONLY_FACTORIES = frozenset({
    "tensor_converter", "tensor_sparse_enc", "tensor_sparse_dec"})


def _residency_class(e: Element) -> str:
    """'device' | 'host' | 'transparent' | 'opaque' for the NNS514
    walk.  Conservative: anything unrecognized is opaque (stops the
    walk without counting as either side), so new elements can never
    produce a false sandwich."""
    f = getattr(e, "FACTORY", "")
    if f in _RESIDENCY_TRANSPARENT:
        return "transparent"
    if f in _HOST_ONLY_FACTORIES:
        return "host"
    if f == "device_src":
        return "device"
    if f == "tensor_transform":
        # jitted XLA chain, device in/out; acceleration=false declares
        # host intent (the reference's ORC flag) — stay conservative
        # and treat it as opaque rather than a device side of a fence
        if not bool(getattr(e, "acceleration", True)):
            return "opaque"
        return "device"
    if f == "tensor_filter":
        fw = str(getattr(e, "framework", "") or "auto")
        if fw in _STATEFUL_FRAMEWORKS:
            return "host"
        if _resolves_jax_xla(fw, getattr(e, "model", None)):
            return "device"
        return "opaque"
    if f == "tensor_decoder":
        dev_render = str(getattr(e, "option7", "")
                         or "").strip().lower() == "device"
        return "device" if dev_render else "host"
    return "opaque"


def _dataflow_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS514: a host-only element sandwiched between two device-
    resident stages.  The upstream stage's output must drain d2h for
    the host element to read it, and the downstream stage re-uploads
    h2d — a residency fence paying one full host round-trip pair per
    frame, in a chain that would otherwise stay in HBM end to end
    (Documentation/dataflow.md).  The walk looks through residency-
    transparent plumbing (queue/tee/mux/...)."""
    cls = {e.name: _residency_class(e) for e in elements}
    down = _adjacency(elements)
    up: Dict[str, List[str]] = {e.name: [] for e in elements}
    for name, outs in down.items():
        for o in outs:
            up[o].append(name)

    def reaches_device(start: str, adj: Dict[str, List[str]]) -> bool:
        seen, stack = set(), list(adj[start])
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            c = cls.get(n, "opaque")
            if c == "device":
                return True
            if c == "transparent":
                stack.extend(adj[n])
        return False

    diags: List[Diagnostic] = []
    for e in elements:
        if cls[e.name] != "host":
            continue
        if not (reaches_device(e.name, up)
                and reaches_device(e.name, down)):
            continue
        what = getattr(e, "FACTORY", type(e).__name__)
        diags.append(Diagnostic.make(
            "NNS514",
            f"{e.name}: host-only element ({what}) between two "
            f"device-resident stages — a residency fence: every frame "
            f"pays a d2h drain to feed it and an h2d upload to leave "
            f"it, in a chain that would otherwise stay in HBM end to "
            f"end",
            element=e.name,
            hint="move the host stage before the first (or after the "
                 "last) device stage, replace it with a device-capable "
                 "equivalent (tensor_transform, tensor_decoder "
                 "option7=device, a jax-xla filter), or accept the "
                 "round-trip knowingly (Documentation/dataflow.md)"))
    return diags


#: plumbing the fusion pass CANNOT look through (runtime/fusion.py
#: requires direct pad adjacency): a queue or tee between segment
#: stages blocks the single-dispatch collapse even though dataflow
#: still works
_FUSION_PLUMBING = frozenset({"queue", "tee"})

#: bounding_boxes schemes with a device render program
#: (decoders/boundingbox.py device_post_program) — the set for which
#: ``option7=device`` makes the decoder a fusable jittable endpoint
_DEVICE_RENDER_SCHEMES = frozenset({
    "mobilenet-ssd-postprocess", "mobilenetssd-pp"})


def _fusion_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS515: a linear transform→filter→decoder segment that WOULD
    collapse into one XLA dispatch per window (runtime/fusion.py) but
    is prevented by a breakable configuration — interposed queue/tee,
    ``share-model=true`` or ``invoke-dynamic`` on the filter, or a
    device-capable decoder scheme left on the host render path.  Warn
    only when every leg of the segment is present and the cause is
    actually breakable: an upstream queue feeding a ``batch>1`` filter
    is load-bearing (NNS501 *requires* it), and a decoder mode without
    a device render program could never fuse, so neither fires."""
    byname = {e.name: e for e in elements}
    down = _adjacency(elements)
    up: Dict[str, List[str]] = {e.name: [] for e in elements}
    for name, outs in down.items():
        for o in outs:
            up[o].append(name)

    def probe(start: str, adj: Dict[str, List[str]], factory: str):
        """First element of ``factory`` reachable from ``start``
        looking only THROUGH fusion-blocking plumbing (queue/tee).
        Returns ``(element | None, crossed_plumbing)``."""
        seen: Set[str] = set()
        stack = [(n, False) for n in adj[start]]
        while stack:
            n, crossed = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            e = byname.get(n)
            f = getattr(e, "FACTORY", "")
            if f in _FUSION_PLUMBING:
                stack.extend((m, True) for m in adj[n])
                continue
            if f == factory:
                return e, crossed
        return None, False

    diags: List[Diagnostic] = []
    for flt in elements:
        if getattr(flt, "FACTORY", "") != "tensor_filter":
            continue
        fw = str(getattr(flt, "framework", "") or "auto")
        if not _resolves_jax_xla(fw, getattr(flt, "model", None)):
            continue
        tr, crossed_up = probe(flt.name, up, "tensor_transform")
        dec, crossed_down = probe(flt.name, down, "tensor_decoder")
        if tr is None or dec is None:
            continue  # not a transform→filter→decoder segment
        cause = hint = None
        batched = int(getattr(flt, "batch", 1) or 1) > 1
        dec_mode = str(getattr(dec, "mode", "") or "")
        dec_scheme = str(getattr(dec, "option1", "") or "").strip().lower()
        dec_device = str(getattr(dec, "option7", "")
                         or "").strip().lower() == "device"
        if bool(getattr(flt, "invoke_dynamic", False)):
            cause = f"invoke-dynamic=true on {flt.name} recompiles " \
                    f"per buffer, so no whole-segment program exists"
            hint = "drop invoke-dynamic (use flexible caps only where " \
                   "shapes truly vary per buffer)"
        elif bool(getattr(flt, "share_model", False)):
            cause = f"share-model=true on {flt.name}: the pooled " \
                    f"instance serves many pipelines, so this " \
                    f"pipeline's transform/decoder stages cannot be " \
                    f"baked into it"
            hint = "give the filter its own instance (share-model=" \
                   "false) or accept per-stage dispatches on the " \
                   "shared path"
        elif (crossed_up and not batched) or crossed_down:
            where = "between the transform and the filter" \
                if crossed_up and not batched \
                else "between the filter and the decoder"
            cause = f"a queue/tee sits {where}: fusion requires " \
                    f"direct pad adjacency"
            hint = "link the segment stages directly (move the " \
                   "queue before the transform / the tee after the " \
                   "decoder)"
        elif dec_mode == "bounding_boxes" and not dec_device \
                and dec_scheme in _DEVICE_RENDER_SCHEMES:
            cause = f"{dec.name} renders on host " \
                    f"(scheme {dec_scheme} has a device render " \
                    f"program, but option7=device is not set)"
            hint = f"set option7=device on {dec.name} so the overlay " \
                   f"fuses into the filter's program"
        if cause is None:
            continue
        diags.append(Diagnostic.make(
            "NNS515",
            f"{tr.name}→{flt.name}→{dec.name}: segment cannot fuse "
            f"into one XLA dispatch per window — {cause}",
            element=flt.name, hint=hint))
    return diags


def _stage_subsets(elements: List[Element]) -> Dict[str, tuple]:
    """Canonical device-index subset of every ``tensor_filter`` with an
    explicit ``devices=`` — the pipeline's declared stages.  Unparseable
    spellings are skipped (start() reports those itself)."""
    from ..parallel.mesh import parse_device_indices

    out: Dict[str, tuple] = {}
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_filter":
            continue
        devs = str(getattr(e, "devices", "") or "").strip()
        if not devs:
            continue
        try:
            out[e.name] = parse_device_indices(devs, 1 << 30)
        except (TypeError, ValueError):
            pass
    return out


def _device_inventory() -> int:
    """Device count of an ALREADY-initialized jax runtime, else 0.
    Lint never initializes jax itself — importing a backend to verify a
    launch line would cost seconds and pin devices; when the embedding
    process already runs one, its inventory is free to read."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return len(jax.devices())
    except Exception:  # noqa: BLE001 - backend not up: no inventory
        return 0


def _stage_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS516: disaggregated pipeline-split topology
    (Documentation/serving.md "Pipeline-split serving").  Three faces:

    - stage subsets that OVERLAP (two explicit ``devices=`` subsets
      sharing chips defeats the disaggregation: the stages contend for
      the same cores and per-stage attribution is unreliable — the
      runtime face is the ``nns_placement_overlap`` gauge) or EXCEED
      the device inventory (only checkable when the embedding process
      already initialized jax; the resolve raises at start() anyway);
    - a ``tensor_if`` offload predicate whose offload branch reaches a
      cross-subset stage filter only THROUGH a host-only element — the
      per-branch extension of the NNS514 residency-fence walk: the
      handoff that should be one device-to-device copy over the device
      channel instead pays a d2h+h2d pair per offloaded frame;
    - the cascade's heavy-stage filter missing ``share-model=true`` —
      every stream that offloads would open its OWN params copy and
      window on the stage subset instead of sharing the pool the
      disaggregation exists to concentrate."""
    diags: List[Diagnostic] = []
    staged = _stage_subsets(elements)

    # face 1a: pairwise overlap between DIFFERENT declared subsets
    names = sorted(staged)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            sa, sb = set(staged[a]), set(staged[b])
            if sa == sb or not (sa & sb):
                continue
            shared = ",".join(map(str, sorted(sa & sb)))
            diags.append(Diagnostic.make(
                "NNS516",
                f"stage subsets overlap: {a} (devices="
                f"{','.join(map(str, staged[a]))}) and {b} (devices="
                f"{','.join(map(str, staged[b]))}) share device(s) "
                f"{shared} — the stages contend for the same chips and "
                f"per-stage attribution is unreliable",
                element=a,
                hint="make the subsets disjoint (that is the point of "
                     "a pipeline split); the runtime counterpart is "
                     "the nns_placement_overlap gauge, and "
                     "NNS_TPU_STRICT_PLACEMENT=1 turns the resolve "
                     "into an error (Documentation/serving.md)"))
    # face 1b: a subset indexing past the inventory (jax already up)
    n_devs = _device_inventory()
    if n_devs:
        for name in names:
            over = [i for i in staged[name] if i >= n_devs]
            if over:
                diags.append(Diagnostic.make(
                    "NNS516",
                    f"{name}: devices="
                    f"{','.join(map(str, staged[name]))} indexes past "
                    f"the device inventory ({n_devs} device(s) "
                    f"visible) — the placement resolve will refuse "
                    f"this at start()",
                    element=name,
                    hint=f"pin indices below {n_devs}, or run on a "
                         f"host with enough devices"))

    byname = {e.name: e for e in elements}
    down = _adjacency(elements)
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_if":
            continue
        off = str(getattr(e, "offload", "") or "").strip().lower()
        if not off:
            continue
        if off not in ("then", "else"):
            diags.append(Diagnostic.make(
                "NNS516",
                f"{e.name}: offload={off!r} — must be 'then' or "
                f"'else' (the branch feeding the heavy stage); "
                f"start() will refuse this",
                element=e.name,
                hint="name the branch that routes to the cross-subset "
                     "stage filter"))
            continue
        pad_name = "src_then" if off == "then" else "src_else"
        start = None
        for sp in e.srcpads:
            if sp.name == pad_name and sp.peer is not None:
                start = sp.peer.element.name
        if start is None:
            continue
        # branch walk (NNS514's residency classes, scoped to the
        # offload branch): look through transparent plumbing, look
        # through host elements while REMEMBERING the crossing, stop
        # at anything opaque.  A staged filter reachable only via a
        # host path lost residency continuity.
        seen: Set[tuple] = set()
        stack = [(start, False)]
        targets: Dict[str, bool] = {}  # stage filter -> host-only path
        while stack:
            n, crossed = stack.pop()
            if (n, crossed) in seen:
                continue
            seen.add((n, crossed))
            if n in staged:
                targets[n] = targets.get(n, True) and crossed
                continue
            c = _residency_class(byname[n])
            if c == "host":
                stack.extend((m, True) for m in down[n])
            elif c == "transparent":
                stack.extend((m, crossed) for m in down[n])
        for tname, via_host in sorted(targets.items()):
            tgt = byname[tname]
            subset = ",".join(map(str, staged[tname]))
            if via_host:
                diags.append(Diagnostic.make(
                    "NNS516",
                    f"{e.name}: the offload branch ({pad_name}) "
                    f"reaches stage filter {tname} (devices={subset}) "
                    f"only through a host-only element — the handoff "
                    f"that should be ONE device-to-device copy over "
                    f"the device channel instead pays a d2h drain plus "
                    f"an h2d upload per offloaded frame (the "
                    f"per-branch face of NNS514)",
                    element=e.name,
                    hint="keep the offload branch device-resident "
                         "(transparent plumbing only) between the "
                         "predicate and the stage filter "
                         "(Documentation/dataflow.md)"))
            if not bool(getattr(tgt, "share_model", False)):
                diags.append(Diagnostic.make(
                    "NNS516",
                    f"{tname}: cascade heavy-stage filter (devices="
                    f"{subset}, fed by {e.name}'s offload branch) "
                    f"without share-model=true — every offloading "
                    f"stream opens its OWN params copy and window on "
                    f"the stage subset instead of sharing the one "
                    f"pool the disaggregation concentrates",
                    element=tname,
                    hint="set share-model=true on the heavy-stage "
                         "filter (Documentation/serving.md "
                         "\"Pipeline-split serving\")"))
    return diags


def _resolves_jax_xla(framework: str, model) -> bool:
    """Whether this filter will open the jax-xla sub-plugin (explicit
    framework, or auto-detection by model extension)."""
    if framework == "jax-xla":
        return True
    if framework not in ("", "auto"):
        return False
    try:
        from ..filters.registry import detect_framework

        return detect_framework(model) == "jax-xla"
    except (ValueError, KeyError):
        return False


def _serving_checks(elements: List[Element]) -> List[Diagnostic]:
    """NNS503/NNS504: shared-model serving topology (runtime/serving.py).
    Two jax-xla filters opening the same model without ``share-model``
    hold two params copies and two executable caches in HBM — and their
    batch windows coalesce independently; ``share-model=true`` on a
    host-side stateful framework shares one user object across
    pipelines, which is only safe for reentrant code."""
    diags: List[Diagnostic] = []
    by_model: Dict[tuple, List[Element]] = {}
    for e in elements:
        if getattr(e, "FACTORY", "") != "tensor_filter":
            continue
        fw = str(getattr(e, "framework", "") or "auto")
        share = bool(getattr(e, "share_model", False))
        if share and fw in _STATEFUL_FRAMEWORKS:
            diags.append(Diagnostic.make(
                "NNS504",
                f"{e.name}: share-model=true with framework={fw} — the "
                f"pooled instance is ONE host-side user object invoked "
                f"from every sharing pipeline's flush context; unless "
                f"the user code is reentrant and stateless this "
                f"corrupts state across streams",
                element=e.name,
                hint="drop share-model (each filter keeps its own "
                     "instance) or port the model to jax-xla, whose "
                     "pooled instances are immutable compiled programs"))
        model = getattr(e, "model", None)
        if share or not isinstance(model, str) or not model:
            continue
        if not _resolves_jax_xla(fw, model):
            continue
        # mirror serving.pool_key: filters differing in ANY of these
        # would land in separate pool entries, so recommending
        # share-model to them would not actually share anything
        key = (model, str(getattr(e, "accelerator", "") or ""),
               str(getattr(e, "custom", "") or ""),
               str(getattr(e, "mesh", "") or ""),
               str(getattr(e, "sharding", "") or ""),
               str(getattr(e, "devices", "") or ""),
               str(getattr(e, "input", "") or ""),
               str(getattr(e, "inputtype", "") or ""),
               str(getattr(e, "output", "") or ""),
               str(getattr(e, "outputtype", "") or ""),
               str(getattr(e, "shared_tensor_filter_key", "") or ""))
        by_model.setdefault(key, []).append(e)
    for key, els in by_model.items():
        if len(els) < 2:
            continue
        model = key[0]
        names = ", ".join(el.name for el in els)
        diags.append(Diagnostic.make(
            "NNS503",
            f"{len(els)} jax-xla filters ({names}) open model "
            f"{model!r} without share-model — each holds its own "
            f"params copy and executable cache in HBM, and their "
            f"batch windows dispatch independently",
            element=els[0].name,
            hint="set share-model=true on all of them to share ONE "
                 "pooled instance and one cross-pipeline batch window "
                 "(Documentation/serving.md)"))
    return diags

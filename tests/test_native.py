"""Native (C++) wire codec: byte-exact parity with the Python fallback.

The native library self-builds on first use (g++, native/Makefile); if
no toolchain exists the whole suite still passes on the Python path.
"""

from fractions import Fraction

import numpy as np
import pytest

import nnstreamer_tpu.nativelib as nativelib
from nnstreamer_tpu.converters import codecs
from nnstreamer_tpu.core import Buffer


@pytest.fixture
def native_lib():
    lib = nativelib.get_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


@pytest.fixture
def python_only(monkeypatch):
    """Force the pure-Python codec path for comparison runs."""
    monkeypatch.setattr(nativelib, "_lib", None)
    monkeypatch.setattr(nativelib, "_tried", True)
    yield


def sample(named=False):
    b = Buffer.of(
        np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        np.array([7, 8, 9], dtype=np.uint8),
        np.array([[1.5, -2.5]], dtype=np.float64),
    )
    if named:
        for i, t in enumerate(b.tensors):
            object.__setattr__(t.spec, "name", f"t{i}")
    return b


class TestNativeParity:
    def test_encode_byte_exact(self, native_lib, monkeypatch):
        b = sample()
        spec = b.spec(rate=Fraction(30))
        enc_native = codecs.protobuf_encode(b, spec)
        monkeypatch.setattr(nativelib, "_lib", None)
        monkeypatch.setattr(nativelib, "_tried", True)
        enc_py = codecs.protobuf_encode(b, spec)
        assert enc_native == enc_py

    def test_encode_byte_exact_with_names(self, native_lib, monkeypatch):
        b = sample(named=True)
        spec = b.spec(rate=Fraction(15))
        enc_native = codecs.protobuf_encode(b, spec)
        monkeypatch.setattr(nativelib, "_lib", None)
        monkeypatch.setattr(nativelib, "_tried", True)
        enc_py = codecs.protobuf_encode(b, spec)
        assert enc_native == enc_py

    def test_decode_matches_python(self, native_lib, monkeypatch):
        b = sample(named=True)
        frame = codecs.protobuf_encode(b, b.spec(rate=Fraction(30)))
        out_nat, spec_nat = codecs.protobuf_decode(frame)
        monkeypatch.setattr(nativelib, "_lib", None)
        monkeypatch.setattr(nativelib, "_tried", True)
        out_py, spec_py = codecs.protobuf_decode(frame)
        assert spec_nat.rate == spec_py.rate == Fraction(30)
        for gn, gp in zip(out_nat.tensors, out_py.tensors):
            np.testing.assert_array_equal(gn.np(), gp.np())
            assert gn.spec.dtype == gp.spec.dtype
            assert gn.spec.name == gp.spec.name

    def test_decode_empty_and_malformed(self, native_lib):
        out, spec = codecs.protobuf_decode(b"")
        assert len(out.tensors) == 0
        with pytest.raises(Exception):
            codecs.protobuf_decode(b"\xff" * 7 + b"\x01")

    def test_decode_field_zero_in_fr_submessage(self, native_lib):
        """Regression: a hostile fr submessage with field number 0 must
        not write rate[-1] (OOB into the ctypes scratch block)."""
        # Tensors { fr { <field 0, varint> 5 ; rate_n=30 ; rate_d=1 } }
        fr = b"\x00\x05" + b"\x08\x1e" + b"\x10\x01"
        frame = b"\x12" + bytes([len(fr)]) + fr
        out, spec = codecs.protobuf_decode(frame)
        assert len(out.tensors) == 0
        assert spec.rate.numerator == 30 and spec.rate.denominator == 1

    # 10-byte varint encoding 2^64-1: an adversarial length that wraps
    # `offset + n + v` if the bounds check adds instead of subtracting
    HUGE = b"\xff" * 9 + b"\x01"

    @pytest.mark.parametrize("frame", [
        b"\x12" + HUGE,                          # fr submessage length
        b"\x1a" + HUGE,                          # tensor submessage length
        b"\x7a" + HUGE,                          # unknown field (skip_field)
        b"\x1a\x0c" + b"\x0a" + HUGE + b"\x00",  # name length inside tensor
        b"\x1a\x0c" + b"\x1a" + HUGE + b"\x00",  # packed-dims length
        b"\x1a\x0c" + b"\x22" + HUGE + b"\x00",  # payload length
        b"\x12\x0c" + b"\x7a" + HUGE + b"\x00",  # skip_field inside fr
    ])
    def test_native_decode_flags_overflowing_lengths(self, native_lib,
                                                     frame):
        """Advisor finding (round 2): uint64 additive bounds checks could
        wrap on an adversarial near-2^64 varint length, passing the check
        and yielding garbage offsets.  All checks are now subtractive, so
        the native parser must report malformed input (-1); the codec
        entry point then falls back to the Python path's tolerant
        truncation rather than surfacing garbage tensors."""
        import ctypes

        from nnstreamer_tpu.nativelib import RANK_LIMIT

        cap = 4
        u8p = ctypes.POINTER(ctypes.c_uint8)
        buf = (ctypes.c_uint8 * len(frame))(*frame)
        rc = native_lib.nns_pb_decode(
            ctypes.cast(buf, u8p), len(frame), cap,
            (ctypes.c_uint64 * cap)(), (ctypes.c_uint64 * cap)(),
            (ctypes.c_uint32 * cap)(),
            (ctypes.c_uint32 * (cap * RANK_LIMIT))(),
            (ctypes.c_uint64 * cap)(), (ctypes.c_uint64 * cap)(),
            (ctypes.c_int32 * 2)(), ctypes.byref(ctypes.c_uint32()))
        assert rc == -1
        # The public entry point then takes the Python path, which either
        # rejects the frame too or truncates tolerantly — never surfaces
        # tensors backed by wrapped (garbage) offsets.
        try:
            out, _ = codecs.protobuf_decode(frame)
        except Exception:
            pass
        else:
            assert all(t.nbytes <= len(frame) for t in out.tensors)

    def test_roundtrip_through_grpc_idl(self, native_lib):
        # the gRPC bridge uses the same codec entry points
        b = sample()
        out, spec = codecs.protobuf_decode(
            codecs.protobuf_encode(b, b.spec(rate=Fraction(10))))
        assert len(out.tensors) == 3

    def test_python_fallback_alone(self, python_only):
        b = sample()
        frame = codecs.protobuf_encode(b, b.spec(rate=Fraction(30)))
        out, spec = codecs.protobuf_decode(frame)
        np.testing.assert_array_equal(out.tensors[0].np(),
                                      b.tensors[0].np())

"""Hardware detection / capability probing.

Parity target: /root/reference/gst/nnstreamer/hw_accel.c (NEON/SIMD
probing via ``getauxval(AT_HWCAP)``) and the accelerator strings the
filter layer parses (``parse_accl_hw_fill``, tensor_filter_common.c).

On this stack the accelerator inventory comes from the XLA backends:
``probe()`` reports every visible platform with device kind, counts,
and per-device memory stats when the runtime exposes them.  The jax-xla
filter's ``accelerator=`` property selects among these
(filters/jax_xla.py ``_parse_accelerator``).
"""

from __future__ import annotations

from typing import Dict, List


def probe() -> Dict[str, List[dict]]:
    """Platform → list of device capability dicts."""
    import jax

    out: Dict[str, List[dict]] = {}
    for platform in ("tpu", "gpu", "cpu"):
        try:
            devs = jax.devices(platform)
        except RuntimeError:
            continue
        entries = []
        for d in devs:
            e = {
                "id": d.id,
                "kind": getattr(d, "device_kind", platform),
                "platform": d.platform,
                "process_index": getattr(d, "process_index", 0),
            }
            try:
                stats = d.memory_stats()
                if stats:
                    e["bytes_limit"] = stats.get("bytes_limit")
                    e["bytes_in_use"] = stats.get("bytes_in_use")
            except Exception:  # noqa: BLE001 — stats are best-effort
                pass
            entries.append(e)
        if entries:
            out[platform] = entries
    return out


def accelerator_available(kind: str) -> bool:
    """True when ``accelerator=<kind>`` would resolve to a device."""
    import jax

    try:
        return bool(jax.devices(kind))
    except RuntimeError:
        return False

"""Wire-format decoders: tensors → flatbuf / protobuf payload streams.

Parity targets:
- /root/reference/ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc
  (213 LoC, mime ``other/flatbuf-tensor``)
- .../tensordec-protobuf.cc (117 LoC, mime ``other/protobuf-tensor``)

Each serializes the whole tensor frame (schema + payloads) into one
self-describing byte buffer — the encode direction of the corresponding
converter sub-plugin in ``nnstreamer_tpu.converters.wirefmt`` (codecs
shared via ``nnstreamer_tpu.converters.codecs``).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..converters.codecs import flatbuf_encode, protobuf_encode
from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, register_decoder


class _WireDecoder(Decoder):
    MIME = ""
    ENCODE: Callable[[Buffer, Optional[TensorsSpec]], bytes] = None

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.new(CapsStruct.make(
            type(self).MIME, framerate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        payload = type(self).ENCODE(buf, in_spec)
        arr = np.frombuffer(payload, np.uint8)
        return Buffer(
            tensors=[Tensor(arr, TensorSpec.from_shape(arr.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))


@register_decoder
class FlatbufDecoder(_WireDecoder):
    MODE = "flatbuf"
    MIME = "other/flatbuf-tensor"
    ENCODE = staticmethod(flatbuf_encode)


@register_decoder
class ProtobufDecoder(_WireDecoder):
    MODE = "protobuf"
    MIME = "other/protobuf-tensor"
    ENCODE = staticmethod(protobuf_encode)

"""Structured logging with element provenance.

Parity target: /root/reference/gst/nnstreamer/nnstreamer_log.c:35-45
(``ml_logi/logw/loge/logf`` + stacktrace on fatal errors).  ``loge_stacktrace``
attaches a formatted Python traceback the way the reference attaches a glibc
``backtrace()``.

``NNS_TPU_LOG_JSON=1`` switches the handler to JSON-lines output (one
object per line: ``ts``, ``level``, ``element``, ``msg``), so log rows
can be joined with the obs metrics registry's samples by the shared
``element`` label (Documentation/observability.md).
"""

from __future__ import annotations

import json
import logging
import os
import time
import traceback

_LOGGER = logging.getLogger("nnstreamer_tpu")

#: marker attribute set on handlers WE installed — the duplicate-import
#: guard keys on it, so re-configuring never stacks a second copy while
#: user/pytest handlers on the same logger are left alone
_HANDLER_TAG = "_nns_tpu_handler"


class JsonLineFormatter(logging.Formatter):
    """One JSON object per record, keyed to join with metrics: the
    ``element`` field carries the same label the obs registry uses."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "element": getattr(record, "element", "-"),
            "msg": record.getMessage(),
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, sort_keys=True)


def _make_handler() -> logging.Handler:
    h = logging.StreamHandler()
    if os.environ.get("NNS_TPU_LOG_JSON", "") == "1":
        h.setFormatter(JsonLineFormatter())
    else:
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s nnstreamer_tpu[%(element)s] "
            "%(message)s", defaults={"element": "-"}))
    setattr(h, _HANDLER_TAG, True)
    return h


def configure(force: bool = False) -> None:
    """Idempotent handler setup.  A module re-import (pytest importing
    the package under a second path, ``importlib.reload``) runs this
    again on the SAME process-wide logger object — so dedup must key on
    our tag, not on module state that the reload just reset.  ``force``
    drops our previous handler first (picks up an NNS_TPU_LOG_JSON
    change mid-process)."""
    ours = [h for h in _LOGGER.handlers if getattr(h, _HANDLER_TAG, False)]
    if ours and not force:
        return
    if not ours and _LOGGER.handlers and not force:
        # the application configured this logger itself before we got
        # here: respect it (the pre-refactor `if not handlers` behavior)
        # — `force=True` is the explicit way to install ours anyway
        return
    for h in ours:
        _LOGGER.removeHandler(h)
    _LOGGER.addHandler(_make_handler())
    _LOGGER.setLevel(os.environ.get("NNS_TPU_LOG_LEVEL", "WARNING").upper())


configure()

ISSUE_URL = "https://github.com/nnstreamer/nnstreamer/issues"


def _log(level: int, msg: str, *args, element: str = "-") -> None:
    _LOGGER.log(level, msg, *args, extra={"element": element})


def logd(msg, *args, element="-"):
    _log(logging.DEBUG, msg, *args, element=element)


def logi(msg, *args, element="-"):
    _log(logging.INFO, msg, *args, element=element)


def logw(msg, *args, element="-"):
    _log(logging.WARNING, msg, *args, element=element)


def loge(msg, *args, element="-"):
    _log(logging.ERROR, msg, *args, element=element)


def loge_stacktrace(msg, *args, element="-"):
    _log(logging.ERROR, msg + "\n" + "".join(traceback.format_stack()),
         *args, element=element)


def logf(msg, *args, element="-"):
    _log(logging.CRITICAL, msg, *args, element=element)

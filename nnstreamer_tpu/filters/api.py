"""Filter sub-plugin ABI.

Parity target: the v1 filter framework ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_filter.h:247-469)
and the C++ base class
(include/nnstreamer_cppplugin_api_filter.hh:165-193): open/close lifecycle,
``invoke``, model-info queries incl. SET_INPUT_INFO reshape, event handling
(model RELOAD), allocate-in-invoke, and the shared-model table
(nnstreamer_plugin_api_filter.h:551-590).

TPU-native redesign: ``invoke`` consumes and produces *device-resident*
``jax.Array``s — the "allocate_in_invoke" pattern of the TensorRT sub-plugin
(tensor_filter_tensorrt.cc:253,396) is the default here, because XLA owns
output allocation and buffers stay in HBM end-to-end.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import TensorsSpec
from ..runtime.events import Event


@dataclasses.dataclass
class FilterProps:
    """Parsed ``tensor_filter`` properties handed to sub-plugin ``open``
    (parity: GstTensorFilterProperties, tensor_filter_common.h:84-109)."""

    framework: str = ""
    model: Any = None          # path(s) or in-process object
    accelerator: str = ""      # e.g. "true:tpu", "cpu"
    custom: str = ""           # free-form custom_properties
    input_spec: Optional[TensorsSpec] = None   # user-forced input info
    output_spec: Optional[TensorsSpec] = None
    shared_key: Optional[str] = None  # shared compiled-model table key
    is_updatable: bool = False        # hot reload allowed
    latency_report: bool = False
    #: mesh spec string ("data:-1", "data:4,model:2"): compile the model
    #: SPMD over a device mesh instead of one chip.  The TPU-native form of
    #: the reference's *remote* tensor_filter (offload to query servers,
    #: tensor_query_client.c:673-741): one invoke spans every chip, XLA
    #: inserts the ICI collectives.
    mesh: str = ""
    #: named param-layout rules (parallel.PARAM_RULES) for the mesh path
    sharding: str = ""
    #: device-index subset for the mesh ("0-3", "4,5,6,7", "0-1,6"):
    #: lays the mesh over a SUBMESH of the platform's devices, so two
    #: filter stages in one pipeline can occupy disjoint device subsets
    #: (stage A on chips 0-3, stage B on 4-7) with device-to-device
    #: handoff — the distributed-pipeline form of SURVEY §7.6.
    devices: str = ""


class FilterError(Exception):
    pass


class FilterSubplugin:
    """Abstract base for filter frameworks (jax-xla, custom-easy, python3…).

    Lifecycle: ``configure(props)`` → ``get_model_info()`` (and optionally
    ``set_input_info``) during negotiation → ``invoke`` per frame → ``close``.
    """

    #: registry name, e.g. "jax-xla"
    NAME: str = ""
    #: hardware the framework can run on (parity: getFrameworkInfo hw list)
    ACCELERATORS: Tuple[str, ...] = ("cpu",)
    #: outputs are freshly allocated by invoke (always true for XLA)
    ALLOCATE_IN_INVOKE: bool = True
    #: sub-plugin implements ``invoke_batched(frames, bucket)`` — run a
    #: micro-batched window of frames as ONE dispatch (see
    #: runtime/batching.py).  Frameworks without it still work under
    #: ``tensor_filter batch>1``: the element falls back to per-frame
    #: ``invoke`` inside the coalesced window (ordering/flush semantics
    #: preserved, no dispatch reduction).
    SUPPORTS_BATCH: bool = False

    def __init__(self):
        self.props: Optional[FilterProps] = None

    # -- lifecycle -----------------------------------------------------------

    def configure(self, props: FilterProps) -> None:
        """Parity: open() / configure_instance()."""
        self.props = props

    def close(self) -> None:
        pass

    # -- shared open (serving pool, runtime/serving.py) ----------------------

    @classmethod
    def open_shared(cls, props: FilterProps) -> "FilterSubplugin":
        """Open an instance for shared use across filter elements (the
        ModelPool path).  Default: a fresh configured instance — the
        pool itself deduplicates per key, so this is enough for
        lightweight frameworks.  Frameworks with heavyweight device
        state (jax-xla: params in HBM, executable caches) override this
        with their own ref-counted table so even pool-external callers
        share ONE instance per model config."""
        sp = cls()
        sp.configure(props)
        return sp

    @classmethod
    def close_shared(cls, sp: "FilterSubplugin") -> None:
        """Release an instance obtained from :meth:`open_shared`
        (default: close it — pairs with the default open)."""
        sp.close()

    # -- model info ----------------------------------------------------------

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        """Return (input_spec, output_spec)."""
        raise NotImplementedError

    def set_input_info(self, in_spec: TensorsSpec
                       ) -> Tuple[TensorsSpec, TensorsSpec]:
        """Reshape the model for a new input schema; return updated
        (in, out).  Parity: GET/SET_INPUT_INFO
        (nnstreamer_plugin_api_filter.h:418-441).  Default: reject."""
        raise FilterError(
            f"{self.NAME}: model cannot be reshaped to {in_spec}")

    # -- hot path ------------------------------------------------------------

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        """Run the model on one frame's tensors (device arrays in, device
        arrays out).  Must be thread-safe w.r.t. ``handle_event``."""
        raise NotImplementedError

    # -- events --------------------------------------------------------------

    def handle_event(self, event: Event) -> None:
        """RELOAD_MODEL etc. (parity: eventHandler,
        nnstreamer_plugin_api_filter.h:351-357)."""


class SharedModelTable:
    """key → opened representation shared across filter instances
    (parity: nnstreamer_filter_shared_model_get/insert/remove/replace,
    nnstreamer_plugin_api_filter.h:551-590)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[str, Any] = {}

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._table.get(key)

    def insert(self, key: str, value: Any) -> Any:
        with self._lock:
            return self._table.setdefault(key, value)

    def replace(self, key: str, value: Any) -> None:
        with self._lock:
            self._table[key] = value

    def remove(self, key: str) -> None:
        with self._lock:
            self._table.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._table.clear()


SHARED_MODELS = SharedModelTable()

"""Tracer hook state — the runtime-facing side of ``obs``.

This module is deliberately tiny and stdlib-only: the runtime hot path
(``runtime/element.py``, ``runtime/batching.py``, ``elements/basic.py``)
imports it at module load and guards every hook site with one global
read::

    from ..obs import hooks as _hooks
    ...
    t = _hooks.tracer
    if t is not None:
        t.pre_chain(self, buf)

When no tracer is attached (``tracer is None``, the default and the
production steady state) a hook site costs one attribute load and one
``is None`` branch — no allocation, no callback, no per-buffer state
(asserted by ``tests/test_obs.py``).  The GstTracer analog: hook points
compiled in, dispatch gated on subscriber presence.
"""

from __future__ import annotations

from typing import Optional

#: the attached tracer (``obs.tracer.LatencyTracer``-shaped), or None.
#: Read UNLOCKED on the hot path; attach/detach are rare control-plane
#: operations and a stale read costs at most one traced/untraced buffer.
tracer: Optional[object] = None


def attach(t) -> None:
    """Attach ``t`` as the process-wide tracer (replaces any previous)."""
    global tracer
    tracer = t


def detach() -> None:
    global tracer
    tracer = None

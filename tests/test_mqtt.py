"""MQTT elements against the in-process broker (the reference likewise
tests against a mocked broker, tests/gstreamer_mqtt)."""

import time
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.edge.mqtt import (
    MiniBroker,
    MqttClient,
    pack_mqtt_buffer,
    unpack_mqtt_buffer,
)
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make


@pytest.fixture
def broker():
    b = MiniBroker()
    yield b
    b.stop()


class TestWire:
    def test_header_roundtrip(self):
        from nnstreamer_tpu.core import Caps

        spec = TensorsSpec.parse("4:2,3", "float32,int32",
                                 rate=Fraction(30))
        b = Buffer.of(np.arange(8, dtype=np.float32).reshape(2, 4),
                      np.array([5, 6, 7], np.int32), pts=777)
        data = pack_mqtt_buffer(b, Caps.from_spec(spec), 100, 200)
        out, ospec, sent = unpack_mqtt_buffer(data)
        assert sent == 200 and out.pts == 777
        assert ospec is not None and ospec.num_tensors == 2
        np.testing.assert_array_equal(out.tensors[0].np(),
                                      b.tensors[0].np())
        assert out.tensors[1].spec.dtype.np_dtype == np.int32


class TestBrokerClient:
    def test_pub_sub(self, broker):
        sub = MqttClient("127.0.0.1", broker.port, "sub")
        sub.subscribe("a/topic")
        pub = MqttClient("127.0.0.1", broker.port, "pub")
        time.sleep(0.1)
        pub.publish("a/topic", b"hello")
        got = None
        for _ in range(50):
            got = sub.recv_publish()
            if got:
                break
        assert got == ("a/topic", b"hello")
        pub.close()
        sub.close()

    def test_wildcard_match(self):
        assert MiniBroker._match("#", "x/y")
        assert MiniBroker._match("a/+/c", "a/b/c")
        assert not MiniBroker._match("a/+/c", "a/b/d")
        assert MiniBroker._match("a/#", "a/b/c/d")


class TestPipelines:
    def test_sink_to_src_pipeline(self, broker):
        spec = TensorsSpec.parse("4:2", "float32", rate=Fraction(30))
        # receiver first, so the subscription exists before publishing
        src = make("mqttsrc", el_name="ms", host="127.0.0.1",
                   port=broker.port, sub_topic="nns/stream",
                   num_buffers=3)
        p2 = Pipeline()
        sink2 = AppSink(name="out")
        p2.add(src, sink2).link(src, sink2)
        p2.start()

        p1 = Pipeline()
        asrc = AppSrc(name="src", spec=spec)
        msink = make("mqttsink", el_name="mk", host="127.0.0.1",
                     port=broker.port, pub_topic="nns/stream")
        p1.add(asrc, msink).link(asrc, msink)
        p1.start()
        time.sleep(0.2)  # let the subscription settle
        bufs = [Buffer.of(np.full((2, 4), i, np.float32), pts=i * 10)
                for i in range(3)]
        for b in bufs:
            asrc.push_buffer(b)
        got = []
        while len(got) < 3:
            b = sink2.pull(timeout=15)
            assert b is not None, f"timed out at {len(got)}/3"
            got.append(b)
        for g, w in zip(got, bufs):
            np.testing.assert_array_equal(g.tensors[0].np(),
                                          w.tensors[0].np())
            assert g.pts == w.pts
            assert g.tensors[0].spec.dtype.np_dtype == np.float32
        assert src.last_latency_us is not None
        p1.stop()
        p2.stop()

"""tensor_src_sensor (tensor_src_iio analog) driven against a mock IIO
sysfs tree — the reference's own test strategy
(tests/nnstreamer_source/unittest_src_iio.cc builds a fake sysfs).
"""

import os
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink
from nnstreamer_tpu.elements.sensorsrc import (
    register_sensor,
    unregister_sensor,
)
from nnstreamer_tpu.runtime import Pipeline, parse_launch
from nnstreamer_tpu.runtime.registry import make


def make_iio_dir(tmp_path, values, scales=None, enables=None, freq=None):
    d = tmp_path / "iio:device0"
    d.mkdir()
    (d / "scan_elements").mkdir()
    for name, v in values.items():
        (d / f"in_{name}_raw").write_text(str(v))
        if scales and name in scales:
            s, o = scales[name]
            (d / f"in_{name}_scale").write_text(str(s))
            (d / f"in_{name}_offset").write_text(str(o))
        if enables is not None:
            (d / "scan_elements" / f"in_{name}_en").write_text(
                "1" if enables.get(name, True) else "0")
    if freq is not None:
        (d / "sampling_frequency").write_text(str(freq))
    return str(d)


def run_src(src, n):
    p = Pipeline()
    sink = AppSink(name="out")
    p.add(src, sink).link(src, sink)
    got = []
    with p:
        while len(got) < n:
            b = sink.pull(timeout=10)
            assert b is not None
            got.append(b)
    return got


class TestIIOBackend:
    def test_merged_channels_with_scale_offset(self, tmp_path):
        d = make_iio_dir(tmp_path, {"accel_x": 100, "accel_y": -50},
                         scales={"accel_x": (0.5, 10.0),
                                 "accel_y": (2.0, 0.0)})
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   num_buffers=2)
        got = run_src(src, 2)
        arr = got[0].tensors[0].np()
        assert arr.shape == (1, 2)
        # processed value = (raw + offset) * scale
        np.testing.assert_allclose(arr[0], [(100 + 10) * 0.5, -50 * 2.0])

    def test_raw_mode_no_processing(self, tmp_path):
        d = make_iio_dir(tmp_path, {"volt0": 42},
                         scales={"volt0": (0.25, 1.0)})
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   process=False, num_buffers=1)
        got = run_src(src, 1)
        assert got[0].tensors[0].np()[0, 0] == 42.0

    def test_channel_enable_auto(self, tmp_path):
        d = make_iio_dir(tmp_path, {"a": 1, "b": 2, "c": 3},
                         enables={"a": True, "b": False, "c": True})
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   num_buffers=1)
        got = run_src(src, 1)
        np.testing.assert_allclose(got[0].tensors[0].np()[0], [1.0, 3.0])

    def test_channel_list_selection(self, tmp_path):
        d = make_iio_dir(tmp_path, {"a": 1, "b": 2, "c": 3})
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   channels="b", num_buffers=1)
        got = run_src(src, 1)
        assert got[0].tensors[0].np().tolist() == [[2.0]]

    def test_unmerged_one_tensor_per_channel(self, tmp_path):
        d = make_iio_dir(tmp_path, {"x": 5, "y": 6})
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   merge_channels_data=False, buffer_capacity=3,
                   num_buffers=1)
        got = run_src(src, 1)
        assert got[0].num_tensors == 2
        np.testing.assert_allclose(got[0].tensors[0].np(), [5.0] * 3)
        np.testing.assert_allclose(got[0].tensors[1].np(), [6.0] * 3)

    def test_device_frequency_and_rate_caps(self, tmp_path):
        d = make_iio_dir(tmp_path, {"a": 1}, freq=100)
        src = make("tensor_src_sensor", el_name="s", device_dir=d,
                   buffer_capacity=10, num_buffers=2)
        spec = src.output_spec()
        assert spec.rate == Fraction(10)  # 100 Hz / capacity 10
        got = run_src(src, 2)
        assert got[1].pts > got[0].pts

    def test_missing_dir_fails_negotiation(self):
        from nnstreamer_tpu.runtime.element import NegotiationError

        src = make("tensor_src_sensor", el_name="s",
                   device_dir="/nonexistent/iio")
        with pytest.raises(NegotiationError):
            src.output_spec()


class TestCallbackBackend:
    def test_registered_sensor_feeds_pipeline(self):
        state = {"n": 0}

        def read():
            state["n"] += 1
            return np.array([state["n"], -state["n"]], np.float32)

        register_sensor("test_imu", read)
        try:
            p = parse_launch(
                "tensor_src_sensor sensor=test_imu num-buffers=3 name=s ! "
                "tensor_transform mode=arithmetic option=mul:2.0 ! "
                "appsink name=out")
            got = []
            with p:
                while len(got) < 3:
                    b = p["out"].pull(timeout=10)
                    assert b is not None
                    got.append(b)
            # transform applied to live sensor samples
            first = got[0].tensors[0].np()
            assert first.shape == (1, 2)
            assert first[0, 0] == -first[0, 1]
        finally:
            unregister_sensor("test_imu")

"""Decoder sub-plugins (L3): tensor streams → media/semantic streams.

Parity target: the decoder sub-plugin ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_decoder.h:38-99):
``init/exit``, ``setOption``, ``getOutCaps``, ``decode``, registered under a
mode string; sub-plugin inventory per
/root/reference/ext/nnstreamer/tensor_decoder/ (SURVEY.md §2.4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Type

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorsSpec

_lock = threading.Lock()
_decoders: Dict[str, Type["Decoder"]] = {}


# -- single-packed-drain helper ----------------------------------------------
#
# Host decoders read N tensors of one frame (boxes/classes/scores/num,
# heatmaps+offsets, ...).  Draining them one .np() at a time costs N
# device→host crossings per frame — on a remote/tunneled device each
# blocking fetch is a full link round-trip.  ``drain_once`` packs every
# device-resident tensor into ONE uint8 array on the device (a jitted
# bitcast+concat — no math, pure layout) and drains that single array,
# then seeds each source Tensor's host cache from the split so later
# ``.np()`` calls are free.  The ledger sees exactly one d2h row per
# frame with the byte-exact sum of all tensor payloads.

class JitFnCache:
    """Locked, bounded get-or-compile cache for the decoders' jitted
    helper programs (packed drains, pre-reductions), keyed by input
    schema.  Bounded because a genuinely dynamic flexible stream would
    otherwise accumulate one XLA executable per distinct shape without
    limit; at the cap the cache clears wholesale and starts over.  One
    shared implementation — the three decoder caches (pack, yolo
    top-k, pose keypoints) must not each re-grow their own unlocked
    copy of this pattern."""

    def __init__(self, max_entries: int = 64):
        self._lock = threading.Lock()
        self._fns: Dict[tuple, object] = {}
        self._max = max_entries

    def get_or_build(self, key: tuple, build):
        with self._lock:
            fn = self._fns.get(key)
        if fn is not None:
            return fn
        fn = build()  # compile outside the lock (can take seconds)
        with self._lock:
            if len(self._fns) >= self._max:
                self._fns.clear()
            return self._fns.setdefault(key, fn)

    def __len__(self) -> int:
        with self._lock:
            return len(self._fns)


_PACK_CACHE = JitFnCache()


def _pack_fn(key: tuple):
    def build():
        import jax
        import jax.numpy as jnp

        def pack(*xs):
            parts = []
            for x in xs:
                b = x if x.dtype == jnp.uint8 \
                    else jax.lax.bitcast_convert_type(x, jnp.uint8)
                parts.append(b.reshape(-1))
            return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

        return jax.jit(pack)

    return _PACK_CACHE.get_or_build(key, build)


def drain_once(tensors: List[Tensor]) -> List[np.ndarray]:
    """Drain N device-resident tensors with ONE device→host crossing;
    returns their host arrays (and seeds each tensor's host cache, so
    subsequent ``.np()`` reads are free).  Tensors already host-resident
    pass through untouched; with one (or zero) device tensors the plain
    ``.np()`` path is already optimal."""
    dev = [t for t in tensors if t.is_device]
    if len(dev) <= 1:
        return [t.np() for t in tensors]
    key = tuple((t.spec.shape, t.spec.dtype.np_dtype.str) for t in dev)
    packed = Tensor(_pack_fn(key)(*[t.jax() for t in dev]))
    from ..utils.stats import DISPATCH_STATS

    DISPATCH_STATS.count("decoder_pack")
    flat = packed.np()  # the one counted d2h drain
    off = 0
    for t in dev:
        n = t.spec.nbytes
        t.seed_host(flat[off:off + n].view(t.spec.dtype.np_dtype))
        off += n
    return [t.np() for t in tensors]


class Decoder:
    """One decode mode (e.g. image_labeling, bounding_boxes)."""

    MODE = ""

    def __init__(self):
        self.options: List[str] = [""] * 9

    def set_option(self, index: int, value: str) -> None:
        """Parity: option1..option9 properties of tensor_decoder."""
        while len(self.options) <= index:
            self.options.append("")
        self.options[index] = value
        self.options_updated()

    def options_updated(self) -> None:
        pass

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        raise NotImplementedError

    def wants_host_input(self) -> bool:
        """Whether decode() reads the input tensors on host.  True for
        every reference decoder (they are CPU rasterizers); a decoder
        that renders on-device returns False so tensor_decoder skips the
        device→host prefetch entirely."""
        return True

    def prereduce_active(self, buf: Buffer) -> bool:
        """Whether decode() will pre-reduce THIS buffer on device (an
        argmax/top-k/packed drain, so only a small final result — or
        one packed array — crosses to host).  When true,
        tensor_decoder skips the per-tensor host prefetch: prefetching
        payloads the device reduction makes redundant would pay the
        full transfer for data nobody reads."""
        return False

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        raise NotImplementedError


def register_decoder(cls: Type[Decoder]) -> Type[Decoder]:
    if not cls.MODE:
        raise ValueError(f"{cls.__name__} has empty MODE")
    with _lock:
        _decoders[cls.MODE] = cls
    return cls


def find_decoder(mode: str) -> Type[Decoder]:
    _ensure_builtin()
    with _lock:
        try:
            return _decoders[mode]
        except KeyError:
            known = ", ".join(sorted(_decoders))
            raise KeyError(
                f"no decoder mode {mode!r}; known: {known}") from None


def list_decoders():
    _ensure_builtin()
    with _lock:
        return sorted(_decoders)


_builtin_done = False
_builtin_lock = threading.Lock()


def _ensure_builtin() -> None:
    global _builtin_done
    if _builtin_done:
        return
    with _builtin_lock:
        if _builtin_done:
            return
        from . import directvideo, imagelabel  # noqa: F401
        for mod in ("boundingbox", "imagesegment", "pose", "tensorregion",
                    "octetstream", "flexbuf", "wirefmt", "python3"):
            __import__(f"{__name__}.{mod}")
        _builtin_done = True

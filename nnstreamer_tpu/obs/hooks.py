"""Tracer hook state — the runtime-facing side of ``obs``.

This module is deliberately tiny and stdlib-only: the runtime hot path
(``runtime/element.py``, ``runtime/batching.py``, ``elements/basic.py``)
imports it at module load and guards every hook site with one global
read::

    from ..obs import hooks as _hooks
    ...
    t = _hooks.tracer
    if t is not None:
        t.pre_chain(self, buf)

When no tracer is attached (``tracer is None``, the default and the
production steady state) a hook site costs one attribute load and one
``is None`` branch — no allocation, no callback, no per-buffer state
(asserted by ``tests/test_obs.py``).  The GstTracer analog: hook points
compiled in, dispatch gated on subscriber presence.
"""

from __future__ import annotations

import os
from typing import Optional

#: global observability kill switch: ``NNS_TPU_OBS_DISABLE=1`` turns
#: the WHOLE obs layer off for the process — tracer attach no-ops,
#: blocking stat samples stop (``stat-sample-interval-ms``/``latency=1``
#: silently no-op; nns-lint NNS508 warns about exactly that), and the
#: transfer ledger stays inert.  Read once at import: the hot paths
#: bake the decision in, so flipping the env mid-process has no effect.
def _env_disabled() -> bool:
    return os.environ.get("NNS_TPU_OBS_DISABLE",
                          "").strip() not in ("", "0")


DISABLED: bool = _env_disabled()

#: the attached tracer (``obs.tracer.LatencyTracer``-shaped), or None.
#: Read UNLOCKED on the hot path; attach/detach are rare control-plane
#: operations and a stale read costs at most one traced/untraced buffer.
tracer: Optional[object] = None


def obs_disabled() -> bool:
    """Whether the global kill switch is set.  Re-reads the environment
    so control-plane consumers (the nns-lint NNS508 check) see the env
    of THEIR invocation; the runtime hot paths use the import-time
    :data:`DISABLED` constant instead."""
    return DISABLED or _env_disabled()


def attach(t) -> None:
    """Attach ``t`` as the process-wide tracer (replaces any previous).
    A no-op while the global kill switch is set."""
    global tracer
    if DISABLED:
        return
    tracer = t


def detach() -> None:
    global tracer
    tracer = None

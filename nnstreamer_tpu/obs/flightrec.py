"""Flight recorder — an always-on black box for the serving runtime.

The chaos/admission layers (PR 6) *detect* trouble — a circuit breaker
opens, the admission controller hard-sheds, an element errors — but by
the time a human looks, the interesting seconds are gone.  This module
keeps them: a bounded ring buffer of control-plane events (sheds,
breaker transitions, element errors, chaos triggers — each carrying its
cumulative counters, so the ring holds the metric *deltas* of the last
N seconds) that is cheap when idle (no thread, no hot-path hook: events
are pushed only by the rare control-plane paths themselves) and is
dumped as post-hoc evidence when triggered:

- **admission hard-shed** — the shed ramp reached 1.0
  (``runtime/serving.py`` ``_warn_shed``);
- **circuit breaker opening** (``chaos/retrypolicy.py``);
- **uncaught element error** (``Element.post_error``);
- **explicitly** — the metrics server's ``/dump`` endpoint, SIGUSR2
  (:func:`install_signal_handler`), or :meth:`FlightRecorder.trigger`.

A dump is two files in the armed directory: a Perfetto/chrome://tracing
loadable trace (``flightrec-NNN-<reason>-trace.json``: the ring's
events as instant marks, plus — when a latency tracer is attached —
its per-frame spans) and a full metrics-registry snapshot
(``…-snapshot.json``), tying the moment to the exported counters.

Arming: set ``NNS_TPU_FLIGHTREC_DIR=<dir>`` (picked up at first
pipeline start, like ``NNS_TPU_CHAOS``) or call :meth:`FLIGHT.arm
<FlightRecorder.arm>`.  Unarmed, triggers still count and the ring
still records — the ``/dump`` endpoint can read it — but nothing is
written to disk.  Dump writes are rate-limited
(:attr:`FlightRecorder.min_dump_interval_s`) so an error storm yields
a few dumps, not a disk full of them.  The global obs kill switch
(``NNS_TPU_OBS_DISABLE``) turns the recorder off entirely.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import hooks as _hooks


class FlightRecorder:
    """Bounded ring of timestamped events + the trigger/dump machinery."""

    def __init__(self, max_events: int = 4096, horizon_s: float = 120.0,
                 min_dump_interval_s: float = 5.0):
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque(
            maxlen=int(max_events))
        self.horizon_s = float(horizon_s)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.enabled = not _hooks.DISABLED
        self._dir: Optional[str] = None
        self._seq = 0
        self._last_dump_ts = 0.0
        self.triggers: Dict[str, int] = {}
        self.dumps: List[Tuple[str, str]] = []  # (trace, snapshot) paths

    # -- arming --------------------------------------------------------------

    def arm(self, directory: str) -> None:
        """Enable dump-to-disk into ``directory`` (created if needed)."""
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._dir = directory

    def disarm(self) -> None:
        with self._lock:
            self._dir = None

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._dir is not None

    # -- recording (the rare control-plane paths call these) -----------------

    def note(self, kind: str, name: str = "", **args: Any) -> None:
        """Append one event to the ring.  ``args`` should carry the
        caller's cumulative counters (total sheds, breaker opens, ...)
        so the ring doubles as a metric-delta log."""
        if not self.enabled:
            return
        evt = {"ts": time.monotonic(), "wall": time.time(),
               "kind": kind, "name": name, "args": args}
        with self._lock:
            self._events.append(evt)

    def trigger(self, reason: str, name: str = "",
                **args: Any) -> Optional[Tuple[str, str]]:
        """Record a trigger event and — when armed and not rate-limited
        — dump the black box.  Returns the (trace, snapshot) paths of a
        written dump, else None."""
        decision = self._trigger_decision(reason, name, **args)
        if decision is None:
            return None
        directory, seq = decision
        return self._dump_files(directory, reason, seq,
                                self.dump_json(reason))

    def trigger_async(self, reason: str, name: str = "",
                      **args: Any) -> bool:
        """Trigger for latency-critical callers (streaming/submit/retry
        threads): the counting is synchronous (deterministic), but the
        expensive part — registry snapshot, trace serialization, file
        writes — runs on a short-lived thread, and ONLY when a dump is
        actually due (armed, not rate-limited), so an error/shed storm
        costs a counter bump per event, not a thread per event.
        Returns True when a dump was scheduled."""
        decision = self._trigger_decision(reason, name, **args)
        if decision is None:
            return False
        directory, seq = decision

        def _work():
            self._dump_files(directory, reason, seq,
                             self.dump_json(reason))

        from . import prof as _prof

        _prof.named_thread("flightrec", "dump", _work).start()
        return True

    def trigger_dump(self, reason: str = "endpoint") -> dict:
        """Trigger + the full dump document, built ONCE: the same doc
        is written to disk (when armed and not rate-limited) and
        returned to the caller — the ``/dump`` endpoint's path, so the
        response and the on-disk dump cannot disagree."""
        decision = self._trigger_decision(reason)
        doc = self.dump_json(reason)
        if decision is not None:
            self._dump_files(decision[0], reason, decision[1], doc)
        return doc

    def _trigger_decision(
            self, reason: str, name: str = "",
            **args: Any) -> Optional[Tuple[str, int]]:
        """Count the trigger; return (directory, seq) when a dump
        should be written, else None (disabled/unarmed/rate-limited)."""
        if not self.enabled:
            return None
        self.note("trigger", name or reason, reason=reason, **args)
        with self._lock:
            self.triggers[reason] = self.triggers.get(reason, 0) + 1
            directory = self._dir
            now = time.monotonic()
            if directory is None or \
                    now - self._last_dump_ts < self.min_dump_interval_s:
                return None
            self._last_dump_ts = now
            self._seq += 1
            return directory, self._seq

    # -- convenience feeders (the wired trigger paths) -----------------------

    def element_error(self, element: str, err: BaseException) -> None:
        """An error reached an element's bus (``Element.post_error``) —
        called from the erroring STREAMING thread, so the dump is
        offloaded (:meth:`trigger_async`)."""
        if not self.enabled:
            return
        self.note("error", element,
                  error=f"{type(err).__name__}: {err}")
        self.trigger_async("element-error", element)

    def breaker_opened(self, link: str, failures: int,
                       opens: int) -> None:
        """A link's circuit breaker opened (chaos/retrypolicy.py) —
        called on the retry path, dump offloaded."""
        self.note("breaker-open", link, failures=failures, opens=opens)
        self.trigger_async("breaker-open", link)

    def shed(self, pool: str, priority: str, reason: str,
             total_shed: int, hard: bool) -> None:
        """The admission controller shed frames; ``hard`` means the
        shed ramp reached 1.0 — the hard-shed trigger threshold.
        Called on the frame submit path during overload: a synchronous
        dump here would stall the very thread whose SLO breach
        triggered the shed, so it is offloaded."""
        self.note("shed", pool, priority=priority, reason=reason,
                  total_shed=total_shed, hard=hard)
        if hard:
            self.trigger_async("admission-hard-shed", pool,
                               total_shed=total_shed)

    # -- the dump ------------------------------------------------------------

    def events(self) -> List[dict]:
        """Ring contents within the horizon, oldest first."""
        cutoff = time.monotonic() - self.horizon_s
        with self._lock:
            return [dict(e) for e in self._events if e["ts"] >= cutoff]

    def chrome_trace(self) -> dict:
        """The ring as Chrome trace-event JSON: one instant mark per
        event on a dedicated ``flightrec`` lane — merged with the
        attached latency tracer's per-frame spans (same monotonic
        clock) when one is installed, so the dump shows WHAT the
        pipeline was doing around the trigger, not only that it
        triggered."""
        events: List[dict] = [{
            "name": f"{e['kind']}:{e['name']}" if e["name"]
            else e["kind"],
            "cat": "flightrec", "ph": "i", "s": "g",
            "pid": 1, "tid": 0,
            "ts": e["ts"] * 1e6,
            "args": {**e["args"], "wall": e["wall"]},
        } for e in self.events()]
        tracer = _hooks.tracer
        if tracer is not None and hasattr(tracer, "chrome_trace"):
            cutoff_us = (time.monotonic() - self.horizon_s) * 1e6
            try:
                for ev in tracer.chrome_trace().get("traceEvents", ()):
                    if ev.get("ts", 0) >= cutoff_us:
                        events.append(ev)
            except (TypeError, ValueError, KeyError):
                pass  # a half-built tracer record must not kill a dump
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_json(self, reason: str = "endpoint") -> dict:
        """The full dump document (what ``/dump`` serves): trace +
        registry snapshot + trigger accounting."""
        from .metrics import REGISTRY

        with self._lock:
            triggers = dict(self.triggers)
        doc = {
            "reason": reason,
            "time": time.time(),
            "triggers": triggers,
            "trace": self.chrome_trace(),
            "snapshot": REGISTRY.snapshot(),
        }
        # host profiler (obs/prof.py): when the sampler is armed, the
        # dump embeds its last-K-seconds collapsed stacks — a hard-shed
        # or breaker-open dump answers "what was the host doing"
        # without a reproduction
        from .prof import PROFILER

        if PROFILER.running:
            doc["host_stacks"] = PROFILER.ring_collapsed()
        return doc

    def _dump_files(self, directory: str, reason: str, seq: int,
                    doc: dict) -> Optional[Tuple[str, str]]:
        from ..utils.log import logw

        base = os.path.join(directory, f"flightrec-{seq:03d}-{reason}")
        trace_path = base + "-trace.json"
        snap_path = base + "-snapshot.json"
        try:
            with open(trace_path, "w") as f:
                json.dump(doc["trace"], f)
            with open(snap_path, "w") as f:
                json.dump({"reason": doc["reason"], "time": doc["time"],
                           "triggers": doc["triggers"],
                           "snapshot": doc["snapshot"],
                           **({"host_stacks": doc["host_stacks"]}
                              if "host_stacks" in doc else {})}, f)
        except (OSError, TypeError, ValueError) as e:
            # TypeError/ValueError: a ring event carried a
            # non-JSON-serializable arg — the dump fails, the process
            # (and the error being recorded) must not
            logw("flight recorder: cannot write dump under %s: %s",
                 directory, e)
            return None
        with self._lock:
            self.dumps.append((trace_path, snap_path))
        logw("flight recorder: dumped %s (trigger: %s)", trace_path,
             reason)
        return trace_path, snap_path

    def clear(self) -> None:
        """Tests only: drop ring, trigger counts and dump bookkeeping."""
        with self._lock:
            self._events.clear()
            self.triggers.clear()
            self.dumps.clear()
            self._last_dump_ts = 0.0


#: the process-wide recorder every wired trigger path feeds
FLIGHT = FlightRecorder()

_env_checked = False


def maybe_arm_from_env() -> None:
    """``NNS_TPU_FLIGHTREC_DIR=<dir>`` arms the recorder when the first
    pipeline starts (same activation hook as ``NNS_TPU_CHAOS`` /
    ``NNS_TPU_METRICS_PORT``).  Also installs the SIGUSR2 dump handler,
    best effort."""
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    directory = os.environ.get("NNS_TPU_FLIGHTREC_DIR", "").strip()
    if not directory:
        return
    try:
        FLIGHT.arm(directory)
    except OSError as e:
        from ..utils.log import logw

        logw("cannot arm flight recorder on NNS_TPU_FLIGHTREC_DIR=%s: "
             "%s", directory, e)
        return
    install_signal_handler()


def install_signal_handler(signum: Optional[int] = None) -> bool:
    """Dump on a signal (default SIGUSR2) — the attach-a-debugger
    analog for a wedged production process.  Returns False where
    installation is impossible (no such signal on the platform, or not
    the main thread)."""
    import signal as _signal

    signum = signum if signum is not None \
        else getattr(_signal, "SIGUSR2", None)
    if signum is None:
        return False

    def _on_signal(_s, _f):
        # hand off to a thread: the handler preempts the main thread,
        # which may hold FLIGHT._lock or a registry lock — trigger()'s
        # non-reentrant lock acquire + blocking file I/O would wedge
        # the very process the signal is meant to diagnose
        from . import prof as _prof

        _prof.named_thread("flightrec", "signal", FLIGHT.trigger,
                           args=("signal",)).start()

    try:
        _signal.signal(signum, _on_signal)
    except ValueError:
        return False  # signal only works in the main thread
    return True

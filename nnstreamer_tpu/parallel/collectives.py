"""shard_map stream primitives: graph-combinator semantics across chips.

The reference's fan-in/fan-out elements (tensor_mux/merge/demux/split,
SURVEY.md §2.3) operate on streams within one process; its cross-device
composition goes through sockets.  On a mesh, the same dataflow shapes are
collectives over ICI:

- ``merge`` across chips        = all_gather along an axis
- ``mux``  across chips         = all_to_all regrouping
- ``split``/``demux`` across chips = the *sharding itself* (no data motion)
- reduction fan-in              = psum / reduce-scatter
- neighbor streaming (ring)     = ppermute — the building block of ring
  attention-style pipelines where each chip streams its block to the next.

These wrappers exist so pipeline elements can express cross-chip semantics
without touching shard_map directly.
"""

from __future__ import annotations

import functools
from typing import Callable


def _jax():
    import jax

    return jax


def _smap(mesh, fn, in_spec, out_spec):
    jax = _jax()

    # check_vma=False: collectives like all_gather produce replicated
    # outputs that shard_map cannot statically infer as such.
    return jax.shard_map(fn, mesh=mesh, in_specs=in_spec,
                         out_specs=out_spec, check_vma=False)


def all_gather_merge(mesh, axis: str = "data", concat_dim: int = 0):
    """Every chip contributes its shard; every chip sees the merged stream
    (cross-chip tensor_merge with direction=``concat_dim``)."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    spec = [None] * (concat_dim + 1)
    spec[concat_dim] = axis

    def local(x):
        return jax.lax.all_gather(x, axis, axis=concat_dim, tiled=True)

    return _smap(mesh, local, (P(*spec),), P())


def psum_reduce(mesh, axis: str = "data"):
    """Sum-reduce shards across the axis; result replicated (the collective
    behind gradient fan-in and averaging muxes)."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    def local(x):
        return jax.lax.psum(x, axis)

    return _smap(mesh, local, (P(axis),), P())


def ring_shift(mesh, axis: str = "data", shift: int = 1):
    """Each chip hands its block to the next chip on the ring (ppermute) —
    the neighbor-exchange primitive for ring-structured streaming (ring
    attention / pipelined stage handoff)."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    def local(x):
        return jax.lax.ppermute(x, axis, perm)

    return _smap(mesh, local, (P(axis),), P(axis))


def all_to_all_regroup(mesh, axis: str = "data", split_dim: int = 1,
                       concat_dim: int = 0):
    """Transpose which dimension is sharded (cross-chip tensor_mux
    regrouping; also the sequence↔head exchange of all-to-all sequence
    parallelism)."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    in_spec = [None] * (concat_dim + 1)
    in_spec[concat_dim] = axis

    out_spec = [None] * (split_dim + 1)
    out_spec[split_dim] = axis

    def local(x):
        return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                                  concat_axis=concat_dim, tiled=True)

    return _smap(mesh, local, (P(*in_spec),), P(*out_spec))


def ring_attention(mesh, axis: str = "data"):
    """Blockwise ring attention over a sequence sharded across chips.

    Long-context scaling primitive: each chip holds a (B, S/n, H) block of
    Q/K/V; K/V blocks rotate around the ring via ppermute while each chip
    accumulates softmax(QKᵀ)V online (flash-attention style running max /
    normalizer), so attention over the FULL sequence never materializes on
    one chip.  This is the TPU answer to sequence lengths beyond one chip's
    HBM — the capability axis the reference lacks entirely (SURVEY.md §5.7).
    """
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(q, k, v):
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        m = jnp.full(q.shape[:-1], -jnp.inf, dtype=jnp.float32)
        acc = jnp.zeros(q.shape, dtype=jnp.float32)
        denom = jnp.zeros(q.shape[:-1], dtype=jnp.float32)

        def body(i, carry):
            k_blk, v_blk, m, acc, denom = carry
            s = jnp.einsum("bqh,bkh->bqk", q, k_blk).astype(jnp.float32) * scale
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            correction = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            acc = acc * correction[..., None] + jnp.einsum(
                "bqk,bkh->bqh", p, v_blk.astype(jnp.float32))
            denom = denom * correction + jnp.sum(p, axis=-1)
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
            return k_blk, v_blk, m_new, acc, denom

        _, _, _, acc, denom = jax.lax.fori_loop(
            0, n, body, (k, v, m, acc, denom))
        return (acc / denom[..., None]).astype(q.dtype)

    sharded = _smap(mesh, local, (P(None, axis), P(None, axis), P(None, axis)),
                    P(None, axis))
    return jax.jit(sharded)

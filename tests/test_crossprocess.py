"""True cross-PROCESS offload: a query server pipeline in a spawned
python subprocess, the client in this process, over localhost TCP —
the reference's paired-gst-launch-processes SSAT shape
(/root/reference/tests/nnstreamer_edge/query/runTest.sh).
"""

import os
import signal
import subprocess
import sys
import textwrap
import time
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SERVER_SCRIPT = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    spec = TensorsSpec.parse("4:1", "float32")
    register_custom_easy("xp_triple", lambda xs: [xs[0] * 3.0],
                         in_spec=spec, out_spec=spec)
    p = Pipeline(name="xp-server")
    src = make("tensor_query_serversrc", el_name="qsrc",
               connect_type="tcp", host="127.0.0.1", port=0, id=77)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="xp_triple")
    snk = make("tensor_query_serversink", el_name="qsink", id=77)
    p.add(src, flt, snk).link(src, flt, snk)
    p.start()
    print(f"PORT={{src.port}}", flush=True)
    import time
    while True:
        time.sleep(0.2)
""")


@pytest.fixture
def server_proc(tmp_path):
    script = tmp_path / "server.py"
    script.write_text(SERVER_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        err = proc.stderr.read() if proc.poll() is not None else ""
        proc.kill()
        pytest.fail(f"server subprocess did not come up: {err[-800:]}")
    yield port
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


TRACED_SERVER_SCRIPT = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.obs import LatencyTracer
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    # the server host's obs layer: its hook marks ride the reply
    LatencyTracer(sample_every=1).install()
    spec = TensorsSpec.parse("4:1", "float32")
    register_custom_easy("xp_triple", lambda xs: [xs[0] * 3.0],
                         in_spec=spec, out_spec=spec)
    p = Pipeline(name="xp-server")
    src = make("tensor_query_serversrc", el_name="qsrc",
               connect_type="tcp", host="127.0.0.1", port=0, id=78)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="xp_triple")
    snk = make("tensor_query_serversink", el_name="qsink", id=78)
    p.add(src, flt, snk).link(src, flt, snk)
    p.start()
    print(f"PORT={{src.port}}", flush=True)
    import time
    while True:
        time.sleep(0.2)
""")


@pytest.fixture
def traced_server_proc(tmp_path):
    script = tmp_path / "traced_server.py"
    script.write_text(TRACED_SERVER_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        err = proc.stderr.read() if proc.poll() is not None else ""
        proc.kill()
        pytest.fail(f"traced server did not come up: {err[-800:]}")
    yield port
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_distributed_trace_two_processes(traced_server_proc):
    """ISSUE-5 acceptance: a TRUE two-process query round-trip yields
    one merged Chrome trace — the client's network span (local clock)
    nests the remote process's per-element spans, client e2e equals the
    residency sum exactly, and the nns_edge_* link counters account for
    every framed message."""
    import json

    from nnstreamer_tpu.obs import REGISTRY, LatencyTracer
    from nnstreamer_tpu.obs.metrics import LinkMetrics
    from nnstreamer_tpu.obs.tracectx import host_tag

    port = traced_server_proc
    LinkMetrics.clear_all()
    caps = ("other/tensors,format=static,num_tensors=1,"
            "dimensions=4:1,types=float32")
    p = Pipeline(name="xp-client-traced")
    src = AppSrc(name="src", spec=TensorsSpec.parse(
        "4:1", "float32", rate=Fraction(10)))
    # device-channel off: the probe is one extra control frame on the
    # link, and this test pins EXACT per-message link accounting (the
    # true-cross-process handshake would be refused anyway)
    cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
               port=port, connect_type="tcp", timeout=30000, caps=caps,
               device_channel=False)
    snk = AppSink(name="out")
    p.add(src, cli, snk).link(src, cli, snk)
    n = 4
    with LatencyTracer(sample_every=1) as tr:
        with p:
            for i in range(n):
                src.push_buffer(Buffer.of(
                    np.full((1, 4), float(i + 1), np.float32), pts=i))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            got = []
            while True:
                b = snk.pull(timeout=0.5)
                if b is None:
                    break
                got.append(b)
    try:
        assert len(got) == n
        recs = tr.records()
        assert len(recs) == n
        local = host_tag()
        for r in recs:
            # exactness survives absorption: e2e == sum of residencies
            assert sum(r["residency_s"].values()) == pytest.approx(
                r["e2e_s"], abs=1e-6)
            hop = r["remote"][0]
            assert hop["host"] != local  # genuinely another process
            marks = r["marks"]
            cli_in = min(t for t, name, ph in marks
                         if name == "cli" and ph == "chain-in")
            out_in = min(t for t, name, ph in marks
                         if name == "out" and ph == "chain-in")
            # client residency ⊇ network span ⊇ mapped server window
            assert cli_in <= hop["t_out"] <= hop["t_in"] <= out_in
            assert hop["t_out"] <= hop["t2"] <= hop["t3"] <= hop["t_in"]
            assert {nm for _, nm, _ in hop["marks"]} \
                >= {"qsrc", "f", "qsink"}
        # the merged Chrome trace is ONE timeline: remote element spans
        # nest inside their frame's network span
        doc = json.loads(json.dumps(tr.chrome_trace()))
        events = doc["traceEvents"]
        nets = [e for e in events if e["cat"] == "net"]
        assert len(nets) == n
        for net in nets:
            host = net["args"]["host"]
            inner = [e for e in events if e["tid"] == net["tid"]
                     and e["name"].startswith(f"{host}/")
                     and e["cat"] == "element"]
            assert inner
            for e in inner:
                assert e["ts"] >= net["ts"] - 1e-3
                assert e["ts"] + e["dur"] <= \
                    net["ts"] + net["dur"] + 1e-3
        # link accounting: every query/reply framed and counted (caps
        # pinned, so exactly n messages each way), RTT sampled per reply
        row = [r for r in REGISTRY.snapshot()["links"]
               if r["kind"] == "query" and r["link"] == "cli"][0]
        assert row["tx_msgs"] == n and row["rx_msgs"] == n
        assert row["tx_bytes"] > 0 and row["rx_bytes"] > 0
        assert row["rtt"]["count"] == n and row["rtt"]["mean_us"] > 0
    finally:
        LinkMetrics.clear_all()


def test_offload_to_subprocess_server(server_proc):
    port = server_proc
    p = Pipeline(name="xp-client")
    src = AppSrc(name="src", spec=TensorsSpec.parse(
        "4:1", "float32", rate=Fraction(10)))
    cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
               port=port, connect_type="tcp", timeout=30000)
    snk = AppSink(name="out")
    p.add(src, cli, snk).link(src, cli, snk)
    with p:
        for i in range(4):
            src.push_buffer(Buffer.of(
                np.full((1, 4), float(i + 1), np.float32), pts=i))
        src.end_of_stream()
        assert p.wait_eos(timeout=60)
        got = []
        while True:
            b = snk.pull(timeout=0.5)
            if b is None:
                break
            got.append(b)
    assert len(got) == 4
    for i, b in enumerate(got):
        np.testing.assert_array_equal(
            b.tensors[0].np(), np.full((1, 4), 3.0 * (i + 1), np.float32))

import numpy as np


class CustomConverter:
    def convert(self, input_arrays):
        raw = input_arrays[0]
        return [raw.view(np.int16).reshape(1, -1).astype(np.int16)]

#!/usr/bin/env python
"""Detection composite: SSD with on-device decode+NMS+overlay render.

    python examples/detect_overlay.py [out.raw]

Writes one 300x300 RGBA overlay frame (raw bytes) per buffer to the
output file via filesink — the SSAT golden-pipeline shape.
``option7=device`` renders the overlay on the accelerator, which also
lets the whole transform→filter→decoder segment fuse into ONE XLA
dispatch per frame (nns-lint NNS515 warns when a segment like this is
left unfused; Documentation/fusion.md).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main(out_path: str = "/tmp/detect_overlay.raw"):
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.ssd import (
        ssd_anchors,
        ssd_detect_apply,
        ssd_mobilenet_v2_init,
    )
    from nnstreamer_tpu.runtime import parse_launch

    params = ssd_mobilenet_v2_init(jax.random.PRNGKey(0), num_classes=91)
    fs = tuple(int(np.ceil(300 / s)) for s in (16, 32, 64, 128, 256, 512))
    anchors = ssd_anchors(300, fs)

    def detect(p, x):
        boxes, scores, classes = ssd_detect_apply(p, x, anchors, max_out=10)
        num = jnp.sum((scores > 0.25).astype(jnp.int32), axis=-1)
        return boxes, classes, scores, num

    register_model("ssd_demo", detect, params=params,
                   in_shapes=[(1, 300, 300, 3)], in_dtypes=np.float32)

    p = parse_launch(
        "device_src name=src pattern=noise num-buffers=3 ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax-xla model=ssd_demo ! "
        "tensor_decoder mode=bounding_boxes "
        "option1=mobilenet-ssd-postprocess option4=300:300 "
        "option5=300:300 option7=device ! "
        f"filesink location={out_path}")
    p["src"].spec = TensorsSpec.from_shapes([(1, 300, 300, 3)], np.uint8)
    with p:
        assert p.wait_eos(timeout=300)
    size = os.path.getsize(out_path)
    print(f"wrote {size} bytes of RGBA overlay frames to {out_path} "
          f"({size // (300 * 300 * 4)} frames)")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/detect_overlay.raw")

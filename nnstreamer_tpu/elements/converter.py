"""``tensor_converter`` — media streams → tensor streams.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_converter.c
(2451 LoC): per-media parsers (video :1440, audio :1553, text :1641, octet
:1712, flexible-tensor :1805), the zero-copy guarantee for video rows whose
stride needs no 4-byte padding (gsttensor_converter.md "Performance
Characteristics"), ``frames-per-tensor`` batching, and external converter
sub-plugins for other mimetypes (nnstreamer_plugin_api_converter.h:41-85).

TPU-native notes: a converted frame keeps its payload host-side and
zero-copy (numpy view) whenever the source layout is tight; upload to HBM
happens once, at the first device element — or, with ``device=true``, here,
so downstream transform/filter stages consume HBM-resident arrays.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import (
    Buffer,
    Caps,
    CapsStruct,
    DType,
    MediaType,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
)
from ..converters import find_converter
from ..runtime.element import Element, NegotiationError, Pad, StreamError
from ..runtime.registry import register_element

# format string → (channels, dtype); parity: video caps handling in
# gsttensor_converter.c:1440+
VIDEO_FORMATS: Dict[str, Tuple[int, DType]] = {
    "RGB": (3, DType.UINT8), "BGR": (3, DType.UINT8),
    "RGBx": (4, DType.UINT8), "BGRx": (4, DType.UINT8),
    "xRGB": (4, DType.UINT8), "xBGR": (4, DType.UINT8),
    "RGBA": (4, DType.UINT8), "BGRA": (4, DType.UINT8),
    "ARGB": (4, DType.UINT8), "ABGR": (4, DType.UINT8),
    "GRAY8": (1, DType.UINT8),
    "GRAY16_LE": (1, DType.UINT16),
}

AUDIO_FORMATS: Dict[str, DType] = {
    "S8": DType.INT8, "U8": DType.UINT8,
    "S16LE": DType.INT16, "U16LE": DType.UINT16,
    "S32LE": DType.INT32, "U32LE": DType.UINT32,
    "F32LE": DType.FLOAT32, "F64LE": DType.FLOAT64,
}

_MEDIA_MIMES = ("video/x-raw", "audio/x-raw", "text/x-raw",
                "application/octet-stream", "other/tensors", "other/tensor")


@register_element("tensor_converter")
class TensorConverter(Element):
    FACTORY = "tensor_converter"

    def __init__(self, name=None, frames_per_tensor: int = 1,
                 input_dim: str = "", input_type: str = "",
                 set_timestamp: bool = True, mode: str = "", **props):
        self.frames_per_tensor = frames_per_tensor
        self.input_dim = input_dim
        self.input_type = input_type
        self.set_timestamp = set_timestamp
        # mode=custom-code:NAME | custom-script:FILE.py (parity:
        # gsttensor_converter.c "mode" property + tensor_converter_custom.c)
        self.mode = mode
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self._media: Optional[CapsStruct] = None
        self._frame_spec: Optional[TensorSpec] = None  # single-frame schema
        self._out_spec: Optional[TensorsSpec] = None
        self._pending: List[np.ndarray] = []  # frames-per-tensor aggregation
        self._pending_pts: Optional[int] = None
        self._frame_count = 0
        self._stride_pad = 0  # bytes of row padding to strip (video)
        self._ext = None  # external converter sub-plugin
        self._mode_ext = None  # resolved mode= converter (cached)
        self._mode_key = None

    # -- negotiation ---------------------------------------------------------

    def pad_template_caps(self, pad: Pad) -> Caps:
        if pad.direction.value == "sink":
            from ..converters import registered_mimes

            mimes = _MEDIA_MIMES + tuple(
                m for m in registered_mimes() if m not in _MEDIA_MIMES)
            structs = [CapsStruct.make(m) for m in mimes]
            return Caps(structs=tuple(structs))
        return Caps.any_tensors()

    def set_caps(self, pad: Pad, caps: Caps) -> None:
        if pad.direction.value == "sink":
            self._configure_from_media(caps.first())
        super().set_caps(pad, caps)

    def _configure_from_media(self, s: CapsStruct) -> None:
        n = int(self.frames_per_tensor)
        rate = s.get("framerate", Fraction(0, 1))
        mime = s.mime
        self._stride_pad = 0
        self._ext = None
        if self.mode:
            # resolve once per mode value: custom scripts must not be
            # re-executed (losing state) on every renegotiation
            if self._mode_ext is None or self._mode_key != str(self.mode):
                self._mode_ext = self._resolve_mode(str(self.mode))
                self._mode_key = str(self.mode)
            self._ext = self._mode_ext
            self._media = s
            self._frame_spec = None
            self._out_spec = self._ext.get_out_config(s)
            return
        if mime == "video/x-raw":
            fmt = str(s.get("format", "RGB"))
            if fmt not in VIDEO_FORMATS:
                raise NegotiationError(
                    f"{self.name}: unsupported video format {fmt!r}")
            ch, dt = VIDEO_FORMATS[fmt]
            w, h = int(s.get("width", 0)), int(s.get("height", 0))
            if w <= 0 or h <= 0:
                raise NegotiationError(
                    f"{self.name}: video caps need width/height")
            row = w * ch * dt.size
            if fmt in ("RGB", "BGR", "GRAY8") and row % 4 != 0:
                # GStreamer pads these rows to 4 bytes: per-frame copy
                # needed (parity: zero-copy rule, gsttensor_converter.md)
                self._stride_pad = 4 - row % 4
            self._frame_spec = TensorSpec(dtype=dt, dims=(ch, w, h, 1))
            self._media = s
        elif mime == "audio/x-raw":
            fmt = str(s.get("format", "S16LE"))
            if fmt not in AUDIO_FORMATS:
                raise NegotiationError(
                    f"{self.name}: unsupported audio format {fmt!r}")
            dt = AUDIO_FORMATS[fmt]
            if self.input_dim:
                # explicit per-buffer schema override (channels:samples)
                self._frame_spec = TensorSpec(
                    dtype=dt,
                    dims=TensorSpec.parse(self.input_dim, str(dt)).dims)
            else:
                chans = int(s.get("channels", 1))
                # samples per incoming buffer from caps; the reference
                # errors on buffers whose size mismatches the negotiated
                # frame (gsttensor_converter.c audio path) — same here via
                # the chain-time size check.
                samples = int(s.get("samples", 1))
                self._frame_spec = TensorSpec(dtype=dt, dims=(chans, samples))
            self._media = s
        elif mime == "text/x-raw":
            size = self._explicit_dims_or_fail("text")
            self._frame_spec = size
            self._media = s
        elif mime == "application/octet-stream":
            self._frame_spec = self._explicit_dims_or_fail("octet")
            self._media = s
        elif mime in ("other/tensors", "other/tensor"):
            # flexible → static passthrough reconfig (chain validates)
            self._media = s
            self._frame_spec = None
            if self.input_dim and self.input_type:
                self._frame_spec = TensorSpec.parse(
                    self.input_dim.split(",")[0],
                    self.input_type.split(",")[0])
        else:
            self._ext = find_converter(mime)
            if self._ext is None:
                raise NegotiationError(
                    f"{self.name}: no converter for mime {mime!r}")
            self._media = s
            self._frame_spec = None
        # out spec
        if self._frame_spec is not None:
            dims = list(self._frame_spec.dims)
            if n > 1:
                # batch along the outermost dim (parity: 30fps d=300:300 →
                # 15fps d=300:300:2, gsttensor_aggregator.md analog)
                if len(dims) >= 4 and dims[-1] == 1:
                    dims[-1] = n  # implicit batch slot (video 3:w:h:1)
                else:
                    dims = dims + [n]
            out_rate = Fraction(rate) / n if rate else Fraction(0, 1)
            self._out_spec = TensorsSpec.of(
                self._frame_spec.with_dims(dims), rate=out_rate)
        elif self._ext is not None:
            self._out_spec = self._ext.get_out_config(s)
        else:
            self._out_spec = TensorsSpec(
                format=TensorFormat.FLEXIBLE, rate=Fraction(rate))

    def _resolve_mode(self, mode: str):
        from ..converters import ExternalConverter, find_custom

        kind, _, arg = mode.partition(":")
        if kind == "custom-code":
            fn = find_custom(arg)
            if fn is None:
                raise NegotiationError(
                    f"{self.name}: no custom converter registered as "
                    f"{arg!r}")

            class _CallableConverter(ExternalConverter):
                def get_out_config(self, caps):
                    return TensorsSpec(format=TensorFormat.FLEXIBLE,
                                       rate=caps.get("framerate",
                                                     Fraction(0, 1))
                                       if caps is not None
                                       else Fraction(0, 1))

                def convert(self, buf, caps):
                    return fn(buf)

            return _CallableConverter()
        if kind == "custom-script":
            from ..converters.python3 import Python3Converter

            return Python3Converter(arg)
        raise NegotiationError(
            f"{self.name}: unknown converter mode {mode!r} "
            "(expected custom-code:NAME or custom-script:FILE.py)")

    def _explicit_dims_or_fail(self, kind: str) -> TensorSpec:
        if not self.input_dim:
            raise NegotiationError(
                f"{self.name}: {kind} input needs input-dim"
                f"{'' if kind == 'text' else '/input-type'} property")
        dt = DType.from_string(self.input_type) if self.input_type \
            else DType.UINT8
        return TensorSpec(dtype=dt,
                          dims=TensorSpec.parse(self.input_dim, str(dt)).dims)

    def propose_src_caps(self, pad: Pad) -> Caps:
        if self._out_spec is None:
            raise NegotiationError(f"{self.name}: input caps not set")
        return Caps.from_spec(self._out_spec)

    # -- chain ---------------------------------------------------------------

    def chain(self, pad: Pad, buf: Buffer) -> None:
        if self._ext is not None:
            out = self._ext.convert(buf, self._media)
            self.push(out)
            return
        mime = self._media.mime if self._media else "other/tensors"
        if mime in ("other/tensors", "other/tensor"):
            self._chain_flex_to_static(buf)
            return
        arr = self._media_frame_to_array(buf)
        n = int(self.frames_per_tensor)
        if n <= 1:
            self._push_frame([arr], buf.pts)
        else:
            self._pending.append(arr)
            if self._pending_pts is None:
                self._pending_pts = buf.pts
            if len(self._pending) >= n:
                frames, pts = self._pending, self._pending_pts
                self._pending, self._pending_pts = [], None
                self._push_frame(frames, pts)

    def _media_frame_to_array(self, buf: Buffer) -> np.ndarray:
        spec = self._frame_spec
        t = buf.tensors[0]
        if t._host is not None or t._dev is not None:
            arr = t.np()
            if arr.size * arr.itemsize != spec.nbytes:
                raise StreamError(
                    f"{self.name}: frame size {arr.nbytes} != {spec.nbytes}")
            return arr.reshape(spec.shape)  # zero-copy reshape
        raw = t.tobytes()
        if self._stride_pad:
            ch, w, h = spec.dims[0], spec.dims[1], spec.dims[2]
            row = w * ch * spec.dtype.size
            padded = row + self._stride_pad
            if len(raw) == padded * h:
                a = np.frombuffer(raw, np.uint8).reshape(h, padded)
                raw = np.ascontiguousarray(a[:, :row]).tobytes()
        if len(raw) != spec.nbytes:
            raise StreamError(
                f"{self.name}: payload {len(raw)}B != expected {spec.nbytes}B")
        return np.frombuffer(raw, dtype=spec.dtype.np_dtype).reshape(spec.shape)

    def _push_frame(self, frames: List[np.ndarray], pts: Optional[int]) -> None:
        out_spec = self._out_spec.tensors[0]
        if len(frames) == 1:
            arr = frames[0].reshape(out_spec.shape)
        else:
            arr = np.stack(frames, axis=0).reshape(out_spec.shape)
        if pts is None and self.set_timestamp:
            from ..core import SECOND

            rate = self._out_spec.rate
            pts = int(self._frame_count * SECOND / rate) if rate else 0
        self._frame_count += 1
        self.push(Buffer(tensors=[Tensor(arr, out_spec)], pts=pts))

    def _chain_flex_to_static(self, buf: Buffer) -> None:
        if self._frame_spec is not None:
            tensors = [t.with_spec(self._frame_spec) for t in buf.tensors]
        else:
            tensors = buf.tensors
        self.push(Buffer(tensors=tensors, pts=buf.pts, duration=buf.duration,
                         format=TensorFormat.STATIC, meta=dict(buf.meta)))

    def on_eos(self) -> None:
        # A partial batch at EOS is dropped, matching the reference's
        # GstAdapter behavior (leftover sub-frame data is discarded);
        # chain() has already flushed every complete batch.
        self._pending, self._pending_pts = [], None

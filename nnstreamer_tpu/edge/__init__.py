"""L5 inter-device layer: query offload, edge pub/sub, wire codec.

The reference's "among-device AI" axis (SURVEY.md §2.5): pipelines span
processes and hosts via tensor_query client/server elements and
edgesrc/edgesink pub/sub, over the nnstreamer-edge transport library.
Here the same element graph runs over two TPU-native transports — an
in-process zero-copy hub (device-resident buffers by reference) and TCP
with MetaInfo-headed wire frames (:mod:`.wire`).  Intra-pod scale-out
stays in :mod:`nnstreamer_tpu.parallel` (one jitted computation over the
mesh); this package is the cross-process/cross-host axis.
"""

from .query import (
    EdgeSink,
    EdgeSrc,
    TensorQueryClient,
    TensorQueryServerSink,
    TensorQueryServerSrc,
    query_server_entry,
)
from .transport import (
    ClientConn,
    Envelope,
    InprocClientConn,
    InprocServer,
    ServerTransport,
    TcpClientConn,
    TcpServer,
    connect,
    make_server,
)
from .wire import (
    MSG_CAPS_REQ,
    MSG_CAPS_RES,
    MSG_PUBLISH,
    MSG_QUERY,
    MSG_REPLY,
    MSG_SUBSCRIBE,
    EdgeMessage,
)

try:  # gRPC bridge (parity: ext tensor_src/sink_grpc); gated on grpcio
    from .grpc_bridge import GrpcSink, GrpcSrc  # noqa: F401
except ImportError:  # pragma: no cover - grpcio absent
    GrpcSink = GrpcSrc = None

from .mqtt import MiniBroker, MqttSink, MqttSrc  # noqa: E402,F401

__all__ = [
    "GrpcSink", "GrpcSrc",
    "MiniBroker", "MqttSink", "MqttSrc",
    "EdgeMessage", "Envelope", "ClientConn", "ServerTransport",
    "InprocServer", "InprocClientConn", "TcpServer", "TcpClientConn",
    "connect", "make_server",
    "TensorQueryClient", "TensorQueryServerSrc", "TensorQueryServerSink",
    "EdgeSink", "EdgeSrc", "query_server_entry",
    "MSG_QUERY", "MSG_REPLY", "MSG_SUBSCRIBE", "MSG_PUBLISH",
    "MSG_CAPS_REQ", "MSG_CAPS_RES",
]

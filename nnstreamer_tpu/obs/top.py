"""``nns-top`` — live per-pipeline terminal view (gst-top / NNShark
parity for this runtime).

Renders, per registered pipeline, one row per element: frames/s in/out
(counter deltas between two registry snapshots), queue depth/capacity,
rolling invoke latency, dispatches/s, batch occupancy — plus one row per
serving-pool entry (refcount, attached streams, cross-stream dispatch
rate, frames/dispatch, stream occupancy, parked frames).

Data source:

- ``--connect HOST:PORT`` scrapes the ``/json`` endpoint of any process
  serving its registry (``serve_metrics(port)`` or the
  ``NNS_TPU_METRICS_PORT`` env hook) — observe a running serve bench
  without instrumenting it;
- with no ``--connect``, the *in-process* global registry is read
  (embedding ``top.main(["--once"])`` in a host application or test).
  ``NNS_TPU_METRICS_PORT`` set in the environment doubles as the
  default connect target, so ``NNS_TPU_METRICS_PORT=9464 nns-top``
  observes the process that exported on that port.

``--once`` takes two samples ``--interval`` apart, prints one table and
exits; the default live mode repaints every interval until Ctrl-C.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

CLEAR = "\x1b[2J\x1b[H"


def fetch_snapshot(connect: Optional[str] = None) -> dict:
    """One registry snapshot: scraped over HTTP when ``connect`` is
    given, read from the in-process global registry otherwise."""
    if connect:
        import urllib.request

        url = f"http://{connect}/json"
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return json.loads(resp.read().decode())
    from .metrics import REGISTRY

    return REGISTRY.snapshot()


# -- rate math ---------------------------------------------------------------


def _index(snap: dict) -> Dict[Tuple[str, str], dict]:
    out = {}
    for p in snap.get("pipelines", []):
        for row in p.get("elements", []):
            out[(p["pipeline"], row["element"])] = row
    return out


def _pool_index(snap: dict) -> Dict[str, dict]:
    return {row["pool"]: row for row in snap.get("pools", [])}


def _rate(cur: float, prev: Optional[float], dt: float) -> Optional[float]:
    if prev is None or dt <= 0:
        return None
    return max(cur - prev, 0) / dt


def _fmt(v, width: int, prec: int = 1) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.{prec}f}".rjust(width)
    return str(v).rjust(width)


# -- rendering ---------------------------------------------------------------


def render(cur: dict, prev: Optional[dict] = None) -> str:
    """One terminal table from a snapshot (rates need ``prev``)."""
    dt = (cur.get("time", 0) - prev.get("time", 0)) if prev else 0.0
    prev_rows = _index(prev) if prev else {}
    prev_pools = _pool_index(prev) if prev else {}
    lines: List[str] = []
    hdr = (f"{'ELEMENT':<18}{'FACTORY':<18}{'IN/s':>9}{'OUT/s':>9}"
           f"{'QUEUE':>9}{'LAT µs':>9}{'DISP/s':>9}{'B-OCC':>7}"
           f"{'S-OCC':>7}")
    for p in cur.get("pipelines", []):
        state = "PLAYING" if p.get("playing") else "STOPPED"
        lines.append(f"pipeline {p['pipeline']} [{state}]")
        lines.append("  " + hdr)
        for row in p.get("elements", []):
            pv = prev_rows.get((p["pipeline"], row["element"]), {})
            stats = row.get("stats", {})
            pstats = pv.get("stats", {})
            fin = _rate(stats.get("buffers_in", 0),
                        pstats.get("buffers_in"), dt)
            fout = _rate(stats.get("buffers_out", 0),
                         pstats.get("buffers_out"), dt)
            q = row.get("queue")
            qcol = f"{q['depth']}/{q['capacity']}" if q else None
            f = row.get("filter")
            lat = disp = bocc = socc = None
            if f:
                lat = f["latency_us"] if f["latency_us"] >= 0 else None
                pf = pv.get("filter") or {}
                disp = _rate(f["invokes"], pf.get("invokes"), dt)
                bocc = f["avg_batch_occupancy"]
                socc = f["avg_stream_occupancy"]
            lines.append(
                "  " + f"{row['element']:<18.18}{row['factory']:<18.18}"
                + _fmt(fin, 9) + _fmt(fout, 9)
                + (qcol.rjust(9) if qcol else "-".rjust(9))
                + _fmt(lat, 9, 0) + _fmt(disp, 9) + _fmt(bocc, 7, 2)
                + _fmt(socc, 7, 2))
        lines.append("")
    pools = cur.get("pools", [])
    if pools:
        lines.append(
            f"{'POOL':<28}{'REF':>5}{'STREAMS':>9}{'DISP/s':>9}"
            f"{'FRM/DISP':>10}{'S-OCC':>7}{'PENDING':>9}{'LAT µs':>9}")
        for row in pools:
            s = row["stats"]
            ps = (prev_pools.get(row["pool"]) or {}).get("stats", {})
            disp = _rate(s["invokes"], ps.get("invokes"), dt)
            pend = (row.get("batcher") or {}).get("pending")
            lat = s["latency_us"] if s["latency_us"] >= 0 else None
            lines.append(
                f"{row['pool']:<28.28}" + _fmt(row["refcount"], 5)
                + _fmt(row["streams"], 9) + _fmt(disp, 9)
                + _fmt(s["avg_batch_occupancy"], 10, 2)
                + _fmt(s["avg_stream_occupancy"], 7, 2)
                + _fmt(pend, 9) + _fmt(lat, 9, 0))
        lines.append("")
    if not cur.get("pipelines") and not pools:
        lines.append("(no registered pipelines or pools)")
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nns-top",
        description="Live per-pipeline observability table "
                    "(Documentation/observability.md)")
    p.add_argument("--connect", metavar="HOST:PORT",
                   default=_default_connect(),
                   help="scrape a remote process's /json metrics "
                        "endpoint (default: in-process registry, or "
                        "127.0.0.1:$NNS_TPU_METRICS_PORT when set)")
    p.add_argument("--once", action="store_true",
                   help="print one table (two samples --interval apart) "
                        "and exit")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between samples/repaints (default 2)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="dump the raw snapshot JSON instead of the table")
    return p


def _default_connect() -> Optional[str]:
    port = os.environ.get("NNS_TPU_METRICS_PORT", "")
    return f"127.0.0.1:{port}" if port else None


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    try:
        if args.as_json:
            print(json.dumps(fetch_snapshot(args.connect), indent=1),
                  file=out)
            return 0
        if args.once:
            prev = fetch_snapshot(args.connect)
            time.sleep(max(args.interval, 0.05))
            cur = fetch_snapshot(args.connect)
            print(render(cur, prev), file=out)
            return 0
        prev = None
        while True:
            cur = fetch_snapshot(args.connect)
            if out is sys.stdout and out.isatty():
                out.write(CLEAR)
            print(render(cur, prev), file=out)
            out.flush()
            prev = cur
            time.sleep(max(args.interval, 0.05))
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"nns-top: cannot reach {args.connect}: {e}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

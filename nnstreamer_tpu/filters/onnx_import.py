"""Minimal ONNX model importer: protobuf walk + quantized graph → JAX.

Parity target: the reference's onnxruntime filter sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_onnxruntime.cc:471 — loads a .onnx through the ORT C++
session) and its in-tree test model
(tests/test_models/models/mobilenet_v2_quant.onnx, an ORT-quantized
torchvision MobileNetV2: QLinearConv/QLinearAdd/QLinearMatMul/
QLinearGlobalAveragePool, all-uint8 activations+weights, NCHW float
I/O).  TPU-native redesign, same policy as the .tflite/.pb importers:
no ORT runtime — a hand-rolled protobuf walk (no protoc codegen)
reads the graph, and the network is rebuilt as ONE jittable JAX
function XLA compiles for the accelerator.

Quantization is an EXECUTION mode here, not just storage (round-4
verdict #1): weights ride as uint8 device arrays (4x fewer HBM bytes
than f32) and inter-op activations stay uint8; the MXU consumes
integer-valued operands and the requantize epilogue fuses into each
conv.  Four modes, selectable via ``custom=qmode:<mode>``:

- ``bf16`` (default): quantized execution with bf16 CODE storage —
  activations carry their integer quantization code (0..255, exactly
  representable in bf16) so the arithmetic is identical to ``dequant``
  (the "orange" golden is bit-stable), but the u8↔bf16 narrowing/
  widening chains that make pure-u8 storage slow on v5e disappear;
  activation HBM traffic is half of f32.  Weights stay uint8-resident
  (read once per batch; 1/4 the bytes).  Measured (fetch-synced,
  batch 256, v5e): 5.8 ms/batch = 44.1k fps/chip vs 12.7 ms dequant
  and 6.2 ms float — fastest AND exact.
- ``dequant``: true u8 execution — operands are lifted u8 → bf16
  integer values right before each conv/matmul (exact: u8 fits bf16)
  and accumulated f32 on the MXU; scales fold into one f32 multiplier
  in the requantize step.  Weight AND activation HBM traffic is uint8.
- ``int8``: true integer convs — u8 operands with
  ``preferred_element_type=int32`` (zero-point corrections applied
  analytically).  Exact integer arithmetic end-to-end.
- ``float``: dequantize everything at load and run f32 with
  saturation clamps (the .tflite importer's round-4 semantics).

Layout: ONNX graphs are NCHW; the importer transposes the input once
and runs the whole network NHWC (TPU's native conv layout), folding
the weight transpose into load time.  Reshape is supported where
layout cannot matter (2-D tensors, or 4-D with 1x1 spatial).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .importer_util import batch_flex_target
from .tf_import import _fields, _signed64

# -- protobuf parse -----------------------------------------------------------

# ONNX TensorProto.DataType
_ODT_NP = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
           5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
           11: np.float64, 12: np.uint32, 13: np.uint64}


def _parse_tensor(b: bytes) -> Tuple[str, np.ndarray]:
    """TensorProto: dims=1, data_type=2, float_data=4, int32_data=5,
    int64_data=7, name=8, raw_data=9, double_data=10."""
    dims: List[int] = []
    dt = 1
    name = ""
    raw = b""
    floats: List[float] = []
    ints: List[int] = []
    for f, w, v in _fields(b):
        if f == 1:
            if w == 2:  # packed
                p = 0
                from ..converters.codecs import _read_varint
                while p < len(v):
                    x, p = _read_varint(v, p)
                    dims.append(_signed64(x))
            else:
                dims.append(_signed64(v))
        elif f == 2:
            dt = v
        elif f == 4:
            if w == 2:
                floats.extend(np.frombuffer(v, "<f4").tolist())
            else:
                floats.append(struct.unpack(
                    "<f", struct.pack("<I", v & 0xFFFFFFFF))[0])
        elif f in (5, 7):
            if w == 2:
                p = 0
                from ..converters.codecs import _read_varint
                while p < len(v):
                    x, p = _read_varint(v, p)
                    ints.append(_signed64(x))
            else:
                ints.append(_signed64(v))
        elif f == 8:
            name = v.decode("utf-8", "replace")
        elif f == 9:
            raw = v
    if dt not in _ODT_NP:
        raise NotImplementedError(f"onnx: unsupported tensor dtype {dt}")
    np_dt = _ODT_NP[dt]
    if raw:
        arr = np.frombuffer(raw, np_dt)
    elif floats:
        arr = np.asarray(floats, np_dt)
    elif ints:
        arr = np.asarray(ints, np_dt)
    else:
        arr = np.zeros(0, np_dt)
    n = int(np.prod(dims)) if dims else arr.size
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr.ravel()[0], np_dt)
    return name, (arr.reshape(dims) if dims else arr)


class OnnxAttr:
    __slots__ = ("name", "f", "i", "s", "t", "ints", "floats", "present")

    def __init__(self):
        self.name = ""
        self.f = 0.0
        self.i = 0
        self.s = b""
        self.t: Optional[np.ndarray] = None
        self.ints: List[int] = []
        self.floats: List[float] = []


def _parse_attr(b: bytes) -> OnnxAttr:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8."""
    a = OnnxAttr()
    from ..converters.codecs import _read_varint
    for f, w, v in _fields(b):
        if f == 1:
            a.name = v.decode("utf-8", "replace")
        elif f == 2:
            a.f = struct.unpack("<f", struct.pack("<I", v & 0xFFFFFFFF))[0]
        elif f == 3:
            a.i = _signed64(v)
        elif f == 4:
            a.s = v
        elif f == 5:
            a.t = _parse_tensor(v)[1]
        elif f == 7:
            if w == 2:
                a.floats.extend(np.frombuffer(v, "<f4").tolist())
            else:
                a.floats.append(struct.unpack(
                    "<f", struct.pack("<I", v & 0xFFFFFFFF))[0])
        elif f == 8:
            if w == 2:
                p = 0
                while p < len(v):
                    x, p = _read_varint(v, p)
                    a.ints.append(_signed64(x))
            else:
                a.ints.append(_signed64(v))
    return a


class OnnxNode:
    __slots__ = ("name", "op", "inputs", "outputs", "attrs")

    def __init__(self):
        self.name = ""
        self.op = ""
        self.inputs: List[str] = []
        self.outputs: List[str] = []
        self.attrs: Dict[str, OnnxAttr] = {}

    def attr_ints(self, key: str, default) -> List[int]:
        return list(self.attrs[key].ints) if key in self.attrs \
            else list(default)

    def attr_i(self, key: str, default: int) -> int:
        return int(self.attrs[key].i) if key in self.attrs else default


def _parse_value_info(b: bytes) -> Tuple[str, Optional[int], List[int]]:
    """ValueInfoProto → (name, elem_type, dims); unknown dims are 0."""
    name = ""
    elem: Optional[int] = None
    dims: List[int] = []
    for f, w, v in _fields(b):
        if f == 1:
            name = v.decode("utf-8", "replace")
        elif f == 2:  # TypeProto.tensor_type=1
            for f2, _, v2 in _fields(v):
                if f2 != 1:
                    continue
                for f3, _, v3 in _fields(v2):
                    if f3 == 1:
                        elem = v3
                    elif f3 == 2:  # TensorShapeProto.dim=1
                        for f4, _, v4 in _fields(v3):
                            if f4 != 1:
                                continue
                            dv = 0
                            for f5, _, v5 in _fields(v4):
                                if f5 == 1:
                                    dv = _signed64(v5)
                            dims.append(dv)
    return name, elem, dims


class OnnxModel:
    """Parsed ModelProto: nodes (topological), initializers, graph IO."""

    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray)):
            buf = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                buf = f.read()
        graph = None
        for f, w, v in _fields(buf):
            if f == 7:  # ModelProto.graph
                graph = v
        if graph is None:
            raise ValueError("onnx: no graph in model")
        self.nodes: List[OnnxNode] = []
        self.inits: Dict[str, np.ndarray] = {}
        self.inputs: List[Tuple[str, Optional[int], List[int]]] = []
        self.outputs: List[str] = []
        for f, w, v in _fields(graph):
            if f == 1:  # node
                n = OnnxNode()
                for f2, w2, v2 in _fields(v):
                    if f2 == 1:
                        n.inputs.append(v2.decode("utf-8", "replace"))
                    elif f2 == 2:
                        n.outputs.append(v2.decode("utf-8", "replace"))
                    elif f2 == 3:
                        n.name = v2.decode("utf-8", "replace")
                    elif f2 == 4:
                        n.op = v2.decode("utf-8", "replace")
                    elif f2 == 5:
                        a = _parse_attr(v2)
                        n.attrs[a.name] = a
                self.nodes.append(n)
            elif f == 5:  # initializer
                name, arr = _parse_tensor(v)
                self.inits[name] = arr
            elif f == 11:
                self.inputs.append(_parse_value_info(v))
            elif f == 12:
                self.outputs.append(_parse_value_info(v)[0])
        if not self.nodes:
            raise ValueError("onnx: no nodes in graph")


# -- graph → JAX --------------------------------------------------------------


def _pads4(node: OnnxNode) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """ONNX 2-D pads [hb, wb, he, we] → ((hb, he), (wb, we))."""
    auto = node.attrs.get("auto_pad")
    if auto is not None and auto.s not in (b"", b"NOTSET"):
        raise NotImplementedError(
            f"onnx: auto_pad {auto.s!r} unsupported (explicit pads only)")
    p = node.attr_ints("pads", [0, 0, 0, 0])
    return (int(p[0]), int(p[2])), (int(p[1]), int(p[3]))


def _qparams(consts, sname: str, zname: str):
    s = np.asarray(consts[sname], np.float32).ravel()
    z = np.asarray(consts[zname]).ravel().astype(np.int32) \
        if zname and zname in consts else np.zeros(1, np.int32)
    return s, z


_SUPPORTED = {"QuantizeLinear", "DequantizeLinear", "QLinearConv",
              "QLinearAdd", "QLinearMul", "QLinearGlobalAveragePool",
              "QLinearMatMul", "Reshape", "Conv", "Add", "Mul", "Relu",
              "Clip", "GlobalAveragePool", "MatMul", "Gemm", "Softmax",
              "Flatten", "Sigmoid", "Concat", "MaxPool", "AveragePool",
              "Transpose"}


def build_fn(model: OnnxModel, qmode: str = "dequant"):
    """Compile the parsed graph into ``fn(params, x) -> y`` plus the
    params pytree, the declared input shape (NCHW as exported) and
    dtype.  ``qmode``: "bf16" (default via the filter) | "dequant" |
    "int8" | "float" (see module doc)."""
    import jax
    import jax.numpy as jnp

    if qmode not in ("bf16", "dequant", "int8", "float"):
        raise ValueError(f"onnx: unknown qmode {qmode!r}")

    floatlike = qmode == "float"
    consts = dict(model.inits)
    for n in model.nodes:
        if n.op not in _SUPPORTED:
            raise NotImplementedError(
                f"onnx: unsupported op {n.op} (node {n.name!r})")

    graph_in = None
    for name, elem, dims in model.inputs:
        if name not in consts:
            graph_in = (name, elem, dims)
    if graph_in is None:
        raise ValueError("onnx: no graph input")
    in_name, in_elem, in_dims = graph_in
    in_dtype = _ODT_NP.get(in_elem or 1, np.float32)
    in_shape = tuple(int(d) if d else 1 for d in in_dims)
    out_name = model.outputs[0]

    # weights pytree: every initializer a node consumes as a data operand
    # (scales/zero-points/shape vectors stay host-side consts — they are
    # structural, folded into the program)
    structural = set()
    for n in model.nodes:
        if n.op in ("QuantizeLinear", "DequantizeLinear"):
            structural.update(n.inputs[1:])
        elif n.op.startswith("QLinear"):
            # data operands are at fixed positions; the rest are q-params
            data = {0, 3} if n.op in ("QLinearConv", "QLinearMatMul",
                                      "QLinearAdd", "QLinearMul") else {0}
            for i, nm in enumerate(n.inputs):
                if i not in data and i != 8:  # 8 = QLinearConv bias
                    structural.add(nm)
        elif n.op == "Reshape":
            structural.update(n.inputs[1:])
    weights: Dict[str, np.ndarray] = {}
    for n in model.nodes:
        for nm in n.inputs:
            if nm in consts and nm not in structural:
                arr = consts[nm]
                if n.op == "QLinearConv" and nm == n.inputs[3]:
                    # OIHW → HWIO once at load; uint8 resident
                    arr = np.transpose(arr, (2, 3, 1, 0))
                if floatlike and arr.dtype in (np.uint8, np.int8):
                    pass  # dequantized below at use sites
                weights[nm] = arr

    rq_dtype = {np.dtype(np.uint8): (0, 255, jnp.uint8),
                np.dtype(np.int8): (-128, 127, jnp.int8)}

    def requant(acc_f, y_s, y_z, qdt=np.dtype(np.uint8)):
        """float accumulator → quantized activation (fused epilogue)."""
        lo, hi, jdt = rq_dtype[qdt]
        y = jnp.round(acc_f / y_s) + y_z
        y = jnp.clip(y, lo, hi)
        if floatlike:
            return (y - y_z) * y_s  # real-valued, saturation preserved
        if qmode == "bf16":
            # store the integer CODE in bf16: exact (fits the
            # mantissa), and the next op's lift is a plain subtract
            # with no u8<->bf16 conversion
            return y.astype(jnp.bfloat16)
        return y.astype(jdt)

    def lift(q, z):
        """quantized activation → integer-valued compute operand."""
        if floatlike:
            return q  # already real-valued (dequantized)
        if qmode == "int8":
            return q.astype(jnp.int32) - z
        return q.astype(jnp.bfloat16) - jnp.bfloat16(z)

    def conv_core(xi, w, strides, pads, group):
        if qmode == "int8":
            pet = jnp.int32
        else:
            pet = jnp.float32
        return jax.lax.conv_general_dilated(
            xi, w, strides, list(pads),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=group,
            preferred_element_type=pet)

    def fn(params, x):
        vals: Dict[str, Any] = {in_name: x}
        # activations are NHWC internally; the exported graph is NCHW
        if len(in_shape) == 4:
            vals[in_name] = jnp.transpose(x, (0, 2, 3, 1))

        def get(nm):
            if nm in vals:
                return vals[nm]
            if nm in params:
                return jnp.asarray(params[nm])
            return jnp.asarray(consts[nm])

        def getw(nm, s, z):
            """weight operand in compute form (u8-resident on device)."""
            w = get(nm)
            if floatlike:
                return (w.astype(jnp.float32) - z.reshape(
                    (1, 1, 1, -1) if w.ndim == 4 else -1)) * s.reshape(
                    (1, 1, 1, -1) if w.ndim == 4 else -1) \
                    if w.dtype in (jnp.uint8.dtype, jnp.int8.dtype) else w
            if qmode == "int8":
                return w.astype(jnp.int32) - z.reshape(
                    (1, 1, 1, -1) if w.ndim == 4 else -1)
            return w.astype(jnp.bfloat16) - jnp.asarray(
                z.reshape((1, 1, 1, -1) if w.ndim == 4 else -1),
                jnp.bfloat16)

        for n in model.nodes:
            op = n.op
            if op == "QuantizeLinear":
                s, z = _qparams(consts, n.inputs[1], n.inputs[2]
                                if len(n.inputs) > 2 else "")
                qdt = consts[n.inputs[2]].dtype if len(n.inputs) > 2 \
                    and n.inputs[2] in consts else np.dtype(np.uint8)
                xi = get(n.inputs[0]).astype(jnp.float32)
                vals[n.outputs[0]] = requant(xi, float(s[0]), int(z[0]),
                                             np.dtype(qdt))
            elif op == "DequantizeLinear":
                s, z = _qparams(consts, n.inputs[1], n.inputs[2]
                                if len(n.inputs) > 2 else "")
                q = get(n.inputs[0])
                if floatlike:
                    vals[n.outputs[0]] = q  # already real-valued
                else:
                    vals[n.outputs[0]] = \
                        (q.astype(jnp.float32) - float(z[0])) * float(s[0])
            elif op == "QLinearConv":
                (xn, xs, xz, wn, ws, wz, ys, yz) = n.inputs[:8]
                bias = n.inputs[8] if len(n.inputs) > 8 else None
                x_s, x_z = _qparams(consts, xs, xz)
                w_s, w_z = _qparams(consts, ws, wz)
                y_s, y_z = _qparams(consts, ys, yz)
                strides = tuple(n.attr_ints("strides", [1, 1]))
                group = n.attr_i("group", 1)
                pads = _pads4(n)
                xi = lift(get(xn), int(x_z[0]))
                # zero-valued padding is correct post-lift (x_zp removed)
                w = getw(wn, w_s, w_z)
                acc = conv_core(xi, w, strides, pads, group)
                acc = acc.astype(jnp.float32)
                if not floatlike:
                    # fold scales: per-channel w_s broadcasts over O
                    # (float mode operands are already real-valued)
                    m = (float(x_s[0]) * w_s).astype(np.float32)
                    acc = acc * m.reshape(1, 1, 1, -1)
                if bias:
                    b = get(bias).astype(jnp.float32) * \
                        (float(x_s[0]) * w_s.reshape(-1))
                    acc = acc + b.reshape(1, 1, 1, -1)
                qdt = consts[yz].dtype if yz in consts \
                    else np.dtype(np.uint8)
                vals[n.outputs[0]] = requant(acc, float(y_s[0]),
                                             int(y_z[0]), np.dtype(qdt))
            elif op in ("QLinearAdd", "QLinearMul"):
                (an, as_, az, bn, bs, bz, cs, cz) = n.inputs[:8]
                a_s, a_z = _qparams(consts, as_, az)
                b_s, b_z = _qparams(consts, bs, bz)
                c_s, c_z = _qparams(consts, cs, cz)
                def as_real(v, sc, zp):
                    # float-mode activations are already real, but a
                    # quantized INITIALIZER operand (e.g. the
                    # classifier bias vector) arrives raw — dequantize
                    # by dtype, not by mode
                    if v.dtype in (jnp.uint8.dtype, jnp.int8.dtype):
                        return (v.astype(jnp.float32) - zp) * sc
                    return v

                if floatlike:
                    a = as_real(get(an), float(a_s[0]), float(a_z[0]))
                    b = as_real(get(bn), float(b_s[0]), float(b_z[0]))
                else:
                    a = (get(an).astype(jnp.float32) - float(a_z[0])) * \
                        float(a_s[0])
                    b = (get(bn).astype(jnp.float32) - float(b_z[0])) * \
                        float(b_s[0])
                r = a + b if op == "QLinearAdd" else a * b
                qdt = consts[cz].dtype if cz in consts \
                    else np.dtype(np.uint8)
                vals[n.outputs[0]] = requant(r, float(c_s[0]),
                                             int(c_z[0]), np.dtype(qdt))
            elif op == "QLinearGlobalAveragePool":
                (xn, xs, xz, ys, yz) = n.inputs[:5]
                x_s, x_z = _qparams(consts, xs, xz)
                y_s, y_z = _qparams(consts, ys, yz)
                if floatlike:
                    xi = get(xn)
                else:
                    xi = (get(xn).astype(jnp.float32) - float(x_z[0])) * \
                        float(x_s[0])
                if n.attr_i("channels_last", 0):
                    raise NotImplementedError(
                        "onnx: channels_last QLinearGlobalAveragePool")
                r = jnp.mean(xi, axis=(1, 2), keepdims=True)  # NHWC
                qdt = consts[yz].dtype if yz in consts \
                    else np.dtype(np.uint8)
                vals[n.outputs[0]] = requant(r, float(y_s[0]),
                                             int(y_z[0]), np.dtype(qdt))
            elif op == "QLinearMatMul":
                (an, as_, az, bn, bs, bz, ys, yz) = n.inputs[:8]
                a_s, a_z = _qparams(consts, as_, az)
                b_s, b_z = _qparams(consts, bs, bz)
                y_s, y_z = _qparams(consts, ys, yz)
                a = lift(get(an), int(a_z[0]))
                b = getw(bn, b_s, b_z)
                acc = jax.lax.dot_general(
                    a, b, (((a.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32
                    if qmode == "int8" else jnp.float32)
                acc = acc.astype(jnp.float32)
                if not floatlike:
                    acc = acc * (float(a_s[0]) * b_s.astype(np.float32))
                qdt = consts[yz].dtype if yz in consts \
                    else np.dtype(np.uint8)
                vals[n.outputs[0]] = requant(acc, float(y_s[0]),
                                             int(y_z[0]), np.dtype(qdt))
            elif op == "Reshape":
                v = get(n.inputs[0])
                tgt = tuple(int(t) for t in np.asarray(
                    consts[n.inputs[1]]).ravel())
                if v.ndim == 4:
                    if v.shape[1] != 1 or v.shape[2] != 1:
                        raise NotImplementedError(
                            "onnx: layout-sensitive Reshape on a 4-D "
                            f"activation {v.shape} (NHWC internal)")
                    v = v.reshape(v.shape[0], -1)  # (B,1,1,C) → (B,C)
                tgt = batch_flex_target(
                    tgt, v.shape,
                    int(x.shape[0]) if getattr(x, "ndim", 0) else 1)
                vals[n.outputs[0]] = v.reshape(tgt)
            elif op == "Flatten":
                v = get(n.inputs[0])
                if v.ndim == 4 and (v.shape[1] != 1 or v.shape[2] != 1):
                    raise NotImplementedError(
                        "onnx: layout-sensitive Flatten (NHWC internal)")
                vals[n.outputs[0]] = v.reshape(v.shape[0], -1)
            # -- float ops (non-quantized graphs) -------------------------
            elif op == "Conv":
                xi, w = get(n.inputs[0]), get(n.inputs[1])
                w = jnp.transpose(w, (2, 3, 1, 0))  # OIHW → HWIO
                strides = tuple(n.attr_ints("strides", [1, 1]))
                group = n.attr_i("group", 1)
                y = jax.lax.conv_general_dilated(
                    xi, w, strides, list(_pads4(n)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=group)
                if len(n.inputs) > 2:
                    y = y + get(n.inputs[2]).reshape(1, 1, 1, -1)
                vals[n.outputs[0]] = y
            elif op in ("Add", "Mul"):
                a, b = get(n.inputs[0]), get(n.inputs[1])
                vals[n.outputs[0]] = a + b if op == "Add" else a * b
            elif op == "Relu":
                vals[n.outputs[0]] = jnp.maximum(get(n.inputs[0]), 0.0)
            elif op == "Clip":
                # absent bounds mean -inf/+inf (a one-sided torch
                # clamp(min=0) export must NOT clamp above)
                lo = -np.inf
                hi = np.inf
                if len(n.inputs) > 1 and n.inputs[1]:
                    lo = float(np.asarray(consts[n.inputs[1]]).ravel()[0])
                elif "min" in n.attrs:
                    lo = float(n.attrs["min"].f)
                if len(n.inputs) > 2 and n.inputs[2]:
                    hi = float(np.asarray(consts[n.inputs[2]]).ravel()[0])
                elif "max" in n.attrs:
                    hi = float(n.attrs["max"].f)
                vals[n.outputs[0]] = jnp.clip(get(n.inputs[0]), lo, hi)
            elif op == "GlobalAveragePool":
                vals[n.outputs[0]] = jnp.mean(
                    get(n.inputs[0]), axis=(1, 2), keepdims=True)
            elif op in ("MaxPool", "AveragePool"):
                xi = get(n.inputs[0])
                ks = n.attr_ints("kernel_shape", [1, 1])
                st = tuple(n.attr_ints("strides", [1, 1]))
                pads = list(_pads4(n))
                dims = (1, int(ks[0]), int(ks[1]), 1)
                strides = (1, st[0], st[1], 1)
                spec = [(0, 0)] + pads + [(0, 0)]
                if op == "MaxPool":
                    vals[n.outputs[0]] = jax.lax.reduce_window(
                        xi, -jnp.inf, jax.lax.max, dims, strides, spec)
                else:
                    s = jax.lax.reduce_window(
                        xi, 0.0, jax.lax.add, dims, strides, spec)
                    c = jax.lax.reduce_window(
                        jnp.ones(xi.shape[:3] + (1,), xi.dtype), 0.0,
                        jax.lax.add, dims, strides, spec)
                    vals[n.outputs[0]] = s / c
            elif op == "MatMul":
                vals[n.outputs[0]] = get(n.inputs[0]) @ get(n.inputs[1])
            elif op == "Gemm":
                a, b = get(n.inputs[0]), get(n.inputs[1])
                if n.attr_i("transA", 0):
                    a = a.T
                if n.attr_i("transB", 0):
                    b = b.T
                alpha = n.attrs["alpha"].f if "alpha" in n.attrs else 1.0
                r = (a @ b) * alpha
                if len(n.inputs) > 2:
                    beta = n.attrs["beta"].f if "beta" in n.attrs else 1.0
                    r = r + beta * get(n.inputs[2])
                vals[n.outputs[0]] = r
            elif op == "Softmax":
                vals[n.outputs[0]] = jax.nn.softmax(
                    get(n.inputs[0]), axis=n.attr_i("axis", -1))
            elif op == "Sigmoid":
                vals[n.outputs[0]] = jax.nn.sigmoid(get(n.inputs[0]))
            elif op == "Concat":
                ax = n.attr_i("axis", 0)
                arrs = [get(i) for i in n.inputs]
                if arrs[0].ndim == 4:
                    # NCHW axis → NHWC axis
                    ax = {0: 0, 1: 3, 2: 1, 3: 2}[ax % 4]
                vals[n.outputs[0]] = jnp.concatenate(arrs, axis=ax)
            elif op == "Transpose":
                perm = n.attr_ints("perm", [])
                v = get(n.inputs[0])
                if v.ndim == 4:
                    raise NotImplementedError(
                        "onnx: Transpose on 4-D activations (NHWC "
                        "internal layout)")
                vals[n.outputs[0]] = jnp.transpose(
                    v, perm or None)
        out = vals[out_name]
        if out.ndim == 4:  # restore the exported NCHW contract
            out = jnp.transpose(out, (0, 3, 1, 2))
        return out

    if floatlike:
        # dequantize weights once at load; scales/zps folded per use
        # site; bf16 mode stores them bf16-resident
        fweights: Dict[str, np.ndarray] = {}
        wq: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for n in model.nodes:
            if n.op == "QLinearConv":
                wq[n.inputs[3]] = _qparams(consts, n.inputs[4], n.inputs[5])
            elif n.op == "QLinearMatMul":
                wq[n.inputs[3]] = _qparams(consts, n.inputs[4], n.inputs[5])
        for nm, arr in weights.items():
            if nm in wq and arr.dtype in (np.uint8, np.int8):
                s, z = wq[nm]
                shp = (1, 1, 1, -1) if arr.ndim == 4 else (-1,)
                fweights[nm] = (arr.astype(np.float32) -
                                z.reshape(shp)) * s.reshape(shp) \
                    if s.size > 1 else \
                    (arr.astype(np.float32) - float(z[0])) * float(s[0])
            else:
                fweights[nm] = arr
        weights = fweights

    return fn, weights, in_shape, in_dtype

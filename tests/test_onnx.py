"""ONNX importer tests (round-4 verdict #3): the reference's own
in-tree quantized model through ``framework=onnx``.

Semantic golden parity:
/root/reference/tests/nnstreamer_filter_onnxruntime/runTest.sh drives
mobilenet_v2_quant.onnx on orange.png through onnxruntime and asserts
the label "orange" (unittest_filter_onnxruntime.cc expects class 951);
the same model imported through XLA must agree — in every quantized
execution mode.
"""

import os

import numpy as np
import pytest

MODEL = "/root/reference/tests/test_models/models/mobilenet_v2_quant.onnx"
ORANGE = "/root/reference/tests/test_models/data/orange.raw"
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_model = pytest.mark.skipif(
    not os.path.isfile(MODEL), reason="reference onnx model absent")


def _orange_nchw(batch: int = 1) -> np.ndarray:
    raw = np.fromfile(ORANGE, np.uint8).reshape(1, 224, 224, 3)
    x = raw.astype(np.float32) / 127.5 - 1.0  # reference's transform
    x = np.transpose(x, (0, 3, 1, 2))  # HWC → CHW (reference transpose)
    return np.repeat(x, batch, axis=0)


class TestOnnxParse:
    @needs_model
    def test_parse_counts(self):
        from nnstreamer_tpu.filters.onnx_import import OnnxModel

        m = OnnxModel(MODEL)
        assert len(m.nodes) == 70
        assert len(m.inits) == 349
        name, elem, dims = [i for i in m.inputs
                            if i[0] not in m.inits][-1]
        assert name == "input" and dims == [1, 3, 224, 224]
        assert m.outputs == ["output"]

    def test_unknown_op_raises(self):
        from nnstreamer_tpu.filters.onnx_import import OnnxModel, build_fn

        m = OnnxModel.__new__(OnnxModel)
        m.inits = {}
        m.inputs = [("x", 1, [1, 4])]
        m.outputs = ["y"]
        node = type("N", (), {"op": "LSTM", "name": "n0",
                              "inputs": ["x"], "outputs": ["y"],
                              "attrs": {}})()
        m.nodes = [node]
        with pytest.raises(NotImplementedError, match="LSTM"):
            build_fn(m)

    def test_bad_qmode_raises(self):
        from nnstreamer_tpu.filters.onnx_import import OnnxModel, build_fn

        m = OnnxModel.__new__(OnnxModel)
        m.inits, m.nodes = {}, []
        with pytest.raises(ValueError, match="qmode"):
            build_fn(m, qmode="fp4")


class TestOnnxGolden:
    @needs_model
    @pytest.mark.parametrize("qmode", ["bf16", "dequant", "int8", "float"])
    def test_orange_all_qmodes(self, qmode):
        from nnstreamer_tpu.elements.filter import FilterSingle

        f = FilterSingle(framework="onnx", model=MODEL,
                         custom=f"qmode:{qmode}")
        out = np.asarray(f.invoke([_orange_nchw()])[0])
        assert out.shape == (1, 1000)
        idx = int(np.argmax(out))
        labels = open(LABELS).read().splitlines()
        assert idx == 951, (idx, labels[idx])  # "orange"
        assert "orange" in labels[idx]

    @needs_model
    def test_framework_autodetect_and_alias(self):
        from nnstreamer_tpu.filters.registry import detect_framework, \
            find_filter

        assert detect_framework(MODEL) == "onnx"
        assert find_filter("onnxruntime").NAME == "onnxruntime"

    @needs_model
    def test_batched_inference(self):
        from nnstreamer_tpu.elements.filter import FilterSingle

        f = FilterSingle(framework="onnx", model=MODEL)
        out = np.asarray(f.invoke([_orange_nchw(batch=2)])[0])
        assert out.shape == (2, 1000)
        assert list(np.argmax(out, axis=-1)) == [951, 951]

"""``tensor_if`` — data-dependent stream branching.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_if.c with
- compared-value sources {A_VALUE, TENSOR_TOTAL_VALUE, ALL_TENSORS_TOTAL,
  TENSOR_AVERAGE_VALUE, ALL_TENSORS_AVERAGE, CUSTOM} (gsttensor_if.h:42-55)
- 10 operators incl. ranges (:60-72)
- then/else behaviors {PASSTHROUGH, SKIP, FILL_ZERO, FILL_VALUES,
  REPEAT_PREVIOUS_FRAME, TENSORPICK} (:79-91)
- registrable custom predicate callback (include/tensor_if.h).

TPU design note: the predicate itself evaluates as a jitted on-device
reduction; only the scalar verdict crosses to host to steer routing (the
data plane stays in HBM).  When both branches feed the same downstream
computation, prefer fusing with ``jax.lax.cond`` inside the filter instead
of this element (SURVEY.md §7.5).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor
from ..obs import stagestat as _stagestat
from ..runtime.element import Element, NegotiationError, Pad, StreamError
from ..runtime.events import Event, EventKind
from ..runtime.registry import register_element

# -- custom predicate registry (parity: nns_tensor_if_custom_register) ------

_custom_preds: Dict[str, Callable] = {}
_custom_lock = threading.Lock()


def register_if_callback(name: str, fn: Callable[[Buffer], bool]) -> None:
    with _custom_lock:
        _custom_preds[name] = fn


def unregister_if_callback(name: str) -> None:
    with _custom_lock:
        _custom_preds.pop(name, None)


_OPS = ("eq", "ne", "gt", "ge", "lt", "le",
        "range_inclusive", "range_exclusive",
        "not_in_range_inclusive", "not_in_range_exclusive")


@register_element("tensor_if")
class TensorIf(Element):
    """1 sink → ``src_then`` / ``src_else`` pads."""

    FACTORY = "tensor_if"

    def __init__(self, name=None, compared_value: str = "A_VALUE",
                 compared_value_option: str = "0:0",
                 supplied_value: str = "0",
                 operator: str = "eq",
                 then: str = "PASSTHROUGH", then_option: str = "",
                 else_: str = "SKIP", else_option: str = "",
                 offload: str = "", **props):
        self.compared_value = compared_value
        self.compared_value_option = compared_value_option
        self.supplied_value = supplied_value
        self.operator = operator
        self.then = then
        self.then_option = then_option
        self.else_ = else_
        self.else_option = else_option
        # conditional-cascade marker: offload="then"/"else" names which
        # branch feeds the HEAVY stage (the cross-subset classifier of
        # a detector→tensor_if→classifier cascade).  Every routing
        # decision then counts into the stage store —
        # nns_cascade_offload_ratio is offloaded/total, the fraction of
        # frames whose confidence made them pay for the heavy model.
        self.offload = offload
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad("src_then")
        self.add_src_pad("src_else")
        self._prev: Dict[str, Optional[Buffer]] = {
            "src_then": None, "src_else": None}

    def set_property(self, key, value):
        if key in ("else", "else-option"):
            key = "else_" if key == "else" else "else_option"
        super().set_property(key, value)

    @property
    def then_pad(self) -> Pad:
        return self.srcpads[0]

    @property
    def else_pad(self) -> Pad:
        return self.srcpads[1]

    # -- predicate -----------------------------------------------------------

    @staticmethod
    def _scalar(t: Tensor, kind: str, flat_idx: int = 0) -> float:
        """One predicate scalar from one tensor.

        Device-resident frames reduce on device and pull ONLY the scalar
        verdict across — never ``.np()``, which drains the whole tensor
        to host and records a counted d2h ledger row.  This is the
        module-docstring contract ("only the scalar verdict crosses to
        host"): a conditional cascade keeps ``crossings_per_frame`` at
        zero even though every frame is judged.  Host frames use numpy
        directly.
        """
        if t.is_device:
            try:
                import jax.numpy as jnp

                arr = t.jax()
                if kind == "at":
                    return float(arr.reshape(-1)[flat_idx])
                return float(jnp.sum(arr) if kind == "sum"
                             else jnp.mean(arr))
            except Exception:  # noqa: BLE001 - fall back to the host path
                pass
        a = t.np()
        if kind == "at":
            return float(a.reshape(-1)[flat_idx])
        return float(a.sum() if kind == "sum" else a.mean())

    def _compared(self, buf: Buffer) -> float:
        cv = str(self.compared_value).upper()
        opt = str(self.compared_value_option)
        if cv == "CUSTOM":
            with _custom_lock:
                fn = _custom_preds.get(opt)
            if fn is None:
                raise StreamError(f"{self.name}: no custom callback {opt!r}")
            return 1.0 if fn(buf) else 0.0
        if cv == "A_VALUE":
            # option "<flat_index>:<tensor_index>" (innermost-first flat idx)
            idx_s, _, ti_s = opt.partition(":")
            ti = int(ti_s or 0)
            return self._scalar(buf.tensors[ti], "at", int(idx_s or 0))
        if cv in ("TENSOR_TOTAL_VALUE", "TENSOR_TOTAL"):
            ti = int(opt or 0)
            return self._scalar(buf.tensors[ti], "sum")
        if cv in ("ALL_TENSORS_TOTAL", "ALL_TOTAL"):
            return float(sum(self._scalar(t, "sum") for t in buf.tensors))
        if cv in ("TENSOR_AVERAGE_VALUE", "AVERAGE"):
            ti = int(opt or 0)
            return self._scalar(buf.tensors[ti], "mean")
        if cv in ("ALL_TENSORS_AVERAGE", "ALL_AVERAGE"):
            if any(t.is_device for t in buf.tensors):
                # element-count-weighted mean == mean of the concatenation
                tot = sum(self._scalar(t, "sum") for t in buf.tensors)
                n = sum(int(np.prod(t.spec.shape)) for t in buf.tensors)
                return tot / max(n, 1)
            vals = np.concatenate([t.np().reshape(-1) for t in buf.tensors])
            return float(vals.mean())
        raise StreamError(f"{self.name}: unknown compared-value {cv!r}")

    def _verdict(self, buf: Buffer) -> bool:
        if str(self.compared_value).upper() == "CUSTOM":
            return bool(self._compared(buf))
        x = self._compared(buf)
        sv = [float(v) for v in str(self.supplied_value).split(":")]
        op = str(self.operator).lower()
        if op not in _OPS:
            raise StreamError(f"{self.name}: unknown operator {op!r}")
        if op == "eq":
            return x == sv[0]
        if op == "ne":
            return x != sv[0]
        if op == "gt":
            return x > sv[0]
        if op == "ge":
            return x >= sv[0]
        if op == "lt":
            return x < sv[0]
        if op == "le":
            return x <= sv[0]
        lo, hi = sv[0], sv[1]
        inside_incl = lo <= x <= hi
        inside_excl = lo < x < hi
        if op == "range_inclusive":
            return inside_incl
        if op == "range_exclusive":
            return inside_excl
        if op == "not_in_range_inclusive":
            return not inside_incl
        return not inside_excl

    # -- behaviors -----------------------------------------------------------

    def _apply_behavior(self, behavior: str, option: str, buf: Buffer,
                        pad_name: str) -> Optional[Buffer]:
        b = str(behavior).upper()
        if b == "PASSTHROUGH":
            return buf
        if b == "SKIP":
            return None
        if b == "FILL_ZERO":
            return buf.replace_tensors(
                [Tensor(np.zeros(t.spec.shape, t.spec.dtype.np_dtype),
                        t.spec) for t in buf.tensors])
        if b == "FILL_VALUES":
            v = float(option or 0)
            return buf.replace_tensors(
                [Tensor(np.full(t.spec.shape, v, t.spec.dtype.np_dtype),
                        t.spec) for t in buf.tensors])
        if b in ("REPEAT_PREVIOUS_FRAME", "REPEAT_PREV"):
            prev = self._prev[pad_name]
            if prev is None:
                return None
            return prev.replace_tensors(prev.tensors)
        if b == "TENSORPICK":
            from .combiners import parse_tensorpick

            picks = [i for grp in parse_tensorpick(option) for i in grp]
            return buf.replace_tensors([buf.tensors[i] for i in picks])
        raise StreamError(f"{self.name}: unknown behavior {behavior!r}")

    # -- flow ----------------------------------------------------------------

    def negotiate_src_pads(self) -> None:
        in_caps = self.sinkpad.caps
        for sp in self.srcpads:
            if sp.peer is None or sp.caps is not None:
                continue
            beh = self.then if sp.name == "src_then" else self.else_
            opt = self.then_option if sp.name == "src_then" \
                else self.else_option
            caps = in_caps
            if str(beh).upper() == "TENSORPICK" and self.sinkpad.spec:
                from .combiners import parse_tensorpick

                picks = [i for grp in parse_tensorpick(opt) for i in grp]
                spec = self.sinkpad.spec
                caps = Caps.from_spec(spec.with_tensors(
                    [spec.tensors[i] for i in picks]))
            m = caps.intersect(sp.peer.template)
            if m.is_empty():
                raise NegotiationError(
                    f"{self.name}.{sp.name}: downstream refuses {caps}")
            sp.caps = m.fixate()
            try:
                sp.spec = sp.caps.to_spec()
            except ValueError:
                sp.spec = None
            sp.peer.element.set_caps(sp.peer, sp.caps)

    def start(self) -> None:
        off = str(self.offload or "").strip().lower()
        if off not in ("", "then", "else"):
            raise ValueError(
                f"{self.name}: offload={self.offload!r} must be "
                f"'then' or 'else' (the branch feeding the heavy stage)")
        self.offload = off

    def chain(self, pad: Pad, buf: Buffer) -> None:
        take_then = self._verdict(buf)
        if self.offload:
            # cascade accounting: the DECISION counts (a SKIP on the
            # kept branch still was a routing verdict) — the ratio is
            # over every frame the predicate judged
            _stagestat.record_offload(
                self.pipeline.name if self.pipeline is not None else "",
                self.name,
                take_then == (self.offload == "then"))
        pad_name = "src_then" if take_then else "src_else"
        behavior = self.then if take_then else self.else_
        option = self.then_option if take_then else self.else_option
        out = self._apply_behavior(behavior, option, buf, pad_name)
        if out is None:
            return
        self._prev[pad_name] = out
        target = self.then_pad if take_then else self.else_pad
        if target.peer is not None:
            self.push(out, pad=target)

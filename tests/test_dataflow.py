"""Device-resident dataflow tests (ISSUE 15).

Covers the load-bearing residency model: donation safety (a donated
input is never silently re-read), residency propagation through
queue/tee/mux/demux under concurrent streams, device fan-in with mixed
residency, decoder device pre-reduction + single packed drains (pinned
by ledger row counts), the transform constant-operand cache (zero
steady-state transform h2d), and the edge layer's device channel — the
ICI fast path with its transparent fallback to TCP when the endpoints
do not share a device world.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from nnstreamer_tpu.core import (
    Buffer,
    Caps,
    DonatedTensorError,
    Tensor,
    TensorsSpec,
)
from nnstreamer_tpu.decoders import drain_once
from nnstreamer_tpu.edge import devicechannel as devch
from nnstreamer_tpu.edge.transport import Envelope, _from_wire, _to_wire
from nnstreamer_tpu.edge.wire import EdgeMessage, MSG_QUERY
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.filters.jax_xla import register_model
from nnstreamer_tpu.obs import transfer as xfer
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make


@pytest.fixture(autouse=True)
def _clean_ledger_and_channel():
    xfer.LEDGER.clear()
    devch.reset()
    yield
    xfer.LEDGER.clear()
    devch.reset()


def _drain(sink, timeout=0.5):
    out = []
    while True:
        b = sink.pull(timeout=timeout)
        if b is None:
            return out
        out.append(b)


# -- donation safety ----------------------------------------------------------


class TestDonation:
    def test_donated_tensor_raises_on_reread(self):
        t = Tensor(jnp.arange(8, dtype=jnp.float32))
        t.mark_donated()
        assert t.is_donated
        with pytest.raises(DonatedTensorError):
            t.np()
        with pytest.raises(DonatedTensorError):
            t.jax()

    def test_host_copy_survives_donation(self):
        t = Tensor(jnp.arange(8, dtype=jnp.float32))
        host = t.np()  # independent host copy drained before dispatch
        t.mark_donated()
        np.testing.assert_array_equal(t.np(), host)

    def test_host_tensor_unaffected(self):
        t = Tensor(np.arange(8, dtype=np.float32))
        t.mark_donated()  # XLA copies host args: nothing is consumed
        assert not t.is_donated
        t.np()

    def test_filter_donation_marks_inputs(self):
        """custom=donate: after the dispatch the input buffer's device
        tensors are consumed — a retained reference (tee-shaped reuse)
        raises instead of reading reused HBM."""
        register_model("df_donate", lambda x: x + 1.0,
                       in_shapes=[(1, 4)], in_dtypes=np.float32)
        p = Pipeline()
        spec = TensorsSpec.parse("4:1", "float32")
        src = AppSrc(name="src", spec=spec)
        flt = make("tensor_filter", el_name="f", framework="jax-xla",
                   model="df_donate", custom="donate")
        snk = AppSink(name="out")
        p.add(src, flt, snk).link(src, flt, snk)
        with p:
            buf = Buffer.of(jnp.zeros((1, 4), jnp.float32))
            retained = buf.tensors[0]
            src.push_buffer(buf)
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 1
        np.testing.assert_array_equal(out[0].tensors[0].np(),
                                      np.ones((1, 4), np.float32))
        with pytest.raises(DonatedTensorError):
            retained.np()

    def test_donation_respects_input_combination(self):
        """input-combination excludes a tensor from the dispatch: XLA
        never saw it, so it must NOT be marked donated."""
        register_model("df_donate_combi", lambda x: x * 2.0,
                       in_shapes=[(1, 4)], in_dtypes=np.float32)
        p = Pipeline()
        spec = TensorsSpec.parse("4:1,4:1", "float32,float32")
        src = AppSrc(name="src", spec=spec)
        flt = make("tensor_filter", el_name="f", framework="jax-xla",
                   model="df_donate_combi", custom="donate",
                   input_combination="0")
        snk = AppSink(name="out")
        p.add(src, flt, snk).link(src, flt, snk)
        with p:
            buf = Buffer.of(jnp.zeros((1, 4), jnp.float32),
                            jnp.ones((1, 4), jnp.float32))
            used, unused = buf.tensors
            src.push_buffer(buf)
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 1
        with pytest.raises(DonatedTensorError):
            used.np()
        np.testing.assert_array_equal(unused.np(),
                                      np.ones((1, 4), np.float32))

    def test_pool_dispatch_marks_donation(self):
        """share-model pool windows donate too (PoolEntry._dispatch_group
        mirrors the element paths): inputs consumed by the shared
        batched dispatch raise on re-read."""
        register_model("df_donate_pool", lambda x: x + 1.0,
                       in_shapes=[(1, 4)], in_dtypes=np.float32)
        p = Pipeline()
        spec = TensorsSpec.parse("4:1", "float32")
        src = AppSrc(name="src", spec=spec)
        flt = make("tensor_filter", el_name="f", framework="jax-xla",
                   model="df_donate_pool", custom="donate",
                   share_model=True, batch=2, batch_timeout_ms=5.0)
        snk = AppSink(name="out")
        p.add(src, flt, snk).link(src, flt, snk)
        with p:
            bufs = [Buffer.of(jnp.full((1, 4), float(i)))
                    for i in range(4)]
            retained = [b.tensors[0] for b in bufs]
            for b in bufs:
                src.push_buffer(b)
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 4
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), i + 1.0, np.float32))
        for t in retained:
            with pytest.raises(DonatedTensorError):
                t.np()

    def test_transform_donation(self):
        p = Pipeline(fuse=False)
        spec = TensorsSpec.parse("4:1", "float32")
        src = AppSrc(name="src", spec=spec)
        tf = make("tensor_transform", el_name="t", mode="arithmetic",
                  option="add:1.0", donate=True)
        snk = AppSink(name="out")
        p.add(src, tf, snk).link(src, tf, snk)
        with p:
            buf = Buffer.of(jnp.zeros((1, 4), jnp.float32))
            retained = buf.tensors[0]
            src.push_buffer(buf)
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 1
        np.testing.assert_array_equal(out[0].tensors[0].np(),
                                      np.ones((1, 4), np.float32))
        with pytest.raises(DonatedTensorError):
            retained.jax()


# -- residency propagation ----------------------------------------------------


class TestResidencyPropagation:
    def test_queue_tee_preserve_device_residency(self):
        """device frames through queue ! tee ! 2x appsink stay device
        (references, zero crossings)."""
        p = Pipeline()
        spec = TensorsSpec.parse("4:1", "float32")
        src = AppSrc(name="src", spec=spec)
        q = make("queue", el_name="q")
        tee = make("tee", el_name="tee")
        s1, s2 = AppSink(name="s1"), AppSink(name="s2")
        p.add(src, q, tee, s1, s2)
        p.link(src, q, tee, s1)
        p.link(tee, s2)
        with p:
            for i in range(4):
                src.push_buffer(Buffer.of(jnp.full((1, 4), float(i))))
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            o1, o2 = _drain(s1), _drain(s2)
        assert len(o1) == len(o2) == 4
        for b in o1 + o2:
            assert b.residency == "device"
        # no element drained or re-uploaded anything
        assert xfer.LEDGER.totals(direction="d2h")[0] == 0
        assert xfer.LEDGER.totals(direction="h2d")[0] == 0

    def test_mux_demux_preserve_residency_concurrent(self):
        """two concurrent device streams mux into one frame and demux
        back out, device-resident throughout."""
        p = Pipeline()
        spec = TensorsSpec.parse("4:1", "float32")
        a, b = AppSrc(name="a", spec=spec), AppSrc(name="b", spec=spec)
        mux = make("tensor_mux", el_name="mux")
        demux = make("tensor_demux", el_name="demux")
        s1, s2 = AppSink(name="s1"), AppSink(name="s2")
        p.add(a, b, mux, demux, s1, s2)
        p.link(a, mux)
        p.link(b, mux)
        p.link(mux, demux)
        p.link_pads(demux, "src_0", s1, "sink")
        p.link_pads(demux, "src_1", s2, "sink")
        n = 8
        with p:
            def feed(src, base):
                for i in range(n):
                    src.push_buffer(Buffer.of(
                        jnp.full((1, 4), float(base + i)), pts=i))
                src.end_of_stream()

            ta = threading.Thread(target=feed, args=(a, 0))
            tb = threading.Thread(target=feed, args=(b, 100))
            ta.start(), tb.start()
            ta.join(), tb.join()
            assert p.wait_eos(timeout=20)
            o1, o2 = _drain(s1), _drain(s2)
        assert len(o1) == len(o2) == n
        for buf in o1 + o2:
            assert buf.residency == "device"
        assert xfer.LEDGER.totals(direction="d2h")[0] == 0

    def test_merge_device_with_host_minority(self):
        """tensor_merge concatenates on device as soon as ANY input is
        device-resident: the host branch uploads once, the output is a
        device tensor (no d2h of the device branch)."""
        p = Pipeline()
        spec = TensorsSpec.parse("4:1", "float32")
        a, b = AppSrc(name="a", spec=spec), AppSrc(name="b", spec=spec)
        merge = make("tensor_merge", el_name="m", option="1")
        snk = AppSink(name="out")
        p.add(a, b, merge, snk)
        p.link(a, merge)
        p.link(b, merge)
        p.link(merge, snk)
        with p:
            a.push_buffer(Buffer.of(jnp.zeros((1, 4), jnp.float32)))
            b.push_buffer(Buffer.of(np.ones((1, 4), np.float32)))
            a.end_of_stream(), b.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 1
        assert out[0].residency == "device"
        assert xfer.LEDGER.totals(direction="d2h")[0] == 0
        np.testing.assert_array_equal(
            out[0].tensors[0].np(),
            np.concatenate([np.zeros((1, 4)), np.ones((1, 4))],
                           axis=0).astype(np.float32))


# -- decoder pre-reduction / packed drain ------------------------------------


class TestDecoderDrains:
    def test_drain_once_single_crossing_byte_exact(self):
        ts = [Tensor(jnp.asarray(np.random.rand(10, 4)
                                 .astype(np.float32))),
              Tensor(jnp.asarray(np.arange(10, dtype=np.int32))),
              Tensor(jnp.asarray(np.array([3], np.int32)))]
        outs = drain_once(ts)
        count, nbytes = xfer.LEDGER.totals(direction="d2h")
        assert count == 1
        assert nbytes == sum(t.nbytes for t in ts)
        np.testing.assert_array_equal(outs[1], np.arange(10))
        # seeded host caches: further reads are free
        xfer.LEDGER.clear()
        for t in ts:
            t.np()
        assert xfer.LEDGER.totals(direction="d2h")[0] == 0

    def test_boundingbox_ssd_pp_one_drain_per_decode(self):
        """the boxes/classes/scores/num layout used to drain 4 times
        per frame; now exactly ONE ledger d2h row per decode."""
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes

        d = BoundingBoxes()
        d.set_option(0, "mobilenet-ssd-postprocess")
        boxes = np.random.rand(1, 10, 4).astype(np.float32)
        cls = np.ones((10,), np.float32)
        scr = np.linspace(1.0, 0.3, 10).astype(np.float32)
        num = np.array([10], np.int32)
        dev = Buffer(tensors=[Tensor(jnp.asarray(boxes)),
                              Tensor(jnp.asarray(cls)),
                              Tensor(jnp.asarray(scr)),
                              Tensor(jnp.asarray(num))])
        out = d.decode(dev, None)
        count, nbytes = xfer.LEDGER.totals(direction="d2h")
        assert count == 1, count
        assert nbytes == boxes.nbytes + cls.nbytes + scr.nbytes \
            + num.nbytes
        host = Buffer(tensors=[Tensor(boxes), Tensor(cls), Tensor(scr),
                               Tensor(num)])
        ref = d.decode(host, None)
        assert len(out.meta["detections"]) == len(ref.meta["detections"])
        assert d.prereduce_active(Buffer(
            tensors=[Tensor(jnp.asarray(boxes))]))

    def test_yolo_device_prereduce_matches_host(self):
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes

        d = BoundingBoxes()
        d.set_option(0, "yolov5")
        d.set_option(2, "0.3:0.5")
        d.in_w = d.in_h = 320
        raw = (np.random.rand(1, 200, 13).astype(np.float32)) * 0.7
        host_dets = d._decode_yolo(Buffer(tensors=[Tensor(raw)]),
                                   v8=False)
        xfer.LEDGER.clear()
        dev_dets = d._decode_yolo(
            Buffer(tensors=[Tensor(jnp.asarray(raw))]), v8=False)
        count, nbytes = xfer.LEDGER.totals(direction="d2h")
        assert count == 1
        assert nbytes < raw.nbytes  # pre-reduced: less than the raw out
        assert len(host_dets) == len(dev_dets)
        for h, v in zip(sorted(host_dets, key=lambda x: -x.score),
                        sorted(dev_dets, key=lambda x: -x.score)):
            assert h.class_id == v.class_id
            assert abs(h.score - v.score) < 1e-5

    def test_pose_and_segment_prereduce_match_host(self):
        from nnstreamer_tpu.decoders.imagesegment import ImageSegment
        from nnstreamer_tpu.decoders.pose import PoseEstimation

        p = PoseEstimation()
        p.set_option(3, "heatmap-offset")
        hm = np.random.rand(1, 12, 12, 17).astype(np.float32)
        off = np.random.rand(1, 12, 12, 34).astype(np.float32)
        kh = p._keypoints(Buffer(tensors=[Tensor(hm), Tensor(off)]))
        xfer.LEDGER.clear()
        kd = p._keypoints(Buffer(tensors=[Tensor(jnp.asarray(hm)),
                                          Tensor(jnp.asarray(off))]))
        assert xfer.LEDGER.totals(direction="d2h")[0] == 1
        for a, b in zip(kh, kd):
            assert abs(a["x"] - b["x"]) < 1e-5
            assert abs(a["score"] - b["score"]) < 1e-5

        s = ImageSegment()
        sc = np.random.rand(17, 17, 21).astype(np.float32)
        ref = s.decode(Buffer(tensors=[Tensor(sc)]), None)
        xfer.LEDGER.clear()
        got = s.decode(Buffer(tensors=[Tensor(jnp.asarray(sc))]), None)
        count, nbytes = xfer.LEDGER.totals(direction="d2h")
        assert count == 1
        assert nbytes < sc.nbytes  # (H, W) index map, not (H, W, C)
        np.testing.assert_array_equal(ref.meta["segment_map"],
                                      got.meta["segment_map"])


# -- transform constant cache -------------------------------------------------


class TestTransformSteadyState:
    def test_per_channel_constant_not_reuploaded(self):
        """satellite: steady-state transform h2d ledger rows are zero —
        the per-channel operand is a cached device constant, and
        device-resident frames never re-upload."""
        p = Pipeline(fuse=False)
        spec = TensorsSpec.parse("3:4", "float32")
        src = AppSrc(name="src", spec=spec)
        tf = make("tensor_transform", el_name="norm", mode="arithmetic",
                  option="per-channel-add:1;2;3")
        snk = AppSink(name="out")
        p.add(src, tf, snk).link(src, tf, snk)
        with p:
            # warmup frame pays the compile
            src.push_buffer(Buffer.of(jnp.zeros((4, 3), jnp.float32)))
            assert snk.pull(timeout=20) is not None
            xfer.LEDGER.clear()
            for i in range(8):
                src.push_buffer(Buffer.of(jnp.full((4, 3), float(i))))
            src.end_of_stream()
            assert p.wait_eos(timeout=20)
            out = _drain(snk)
        assert len(out) == 8
        # steady state: no h2d rows attributed to the transform element
        snap = xfer.LEDGER.snapshot()
        tf_h2d = [r for r in snap
                  if r["source"] == "norm" and r["direction"] == "h2d"]
        assert tf_h2d == [], tf_h2d
        np.testing.assert_array_equal(
            out[0].tensors[0].np()[0],
            np.array([1, 2, 3], np.float32))


# -- device channel (ICI fast path) ------------------------------------------


SPEC = TensorsSpec.parse("4:1", "float32")


def _query_rig(tag, server_id, client_kw=None, monkeypatch=None,
               server_fp=None):
    """localhost-TCP query offload rig; returns (server_pipe, make_client)."""
    name = f"devch_double_{tag}"
    register_model(name, lambda x: x * 2.0, in_shapes=[(1, 4)],
                   in_dtypes=np.float32)
    sp = Pipeline(name=f"dcsrv-{tag}")
    ssrc = make("tensor_query_serversrc", el_name="qsrc",
                host="localhost", port=0, connect_type="tcp",
                id=server_id, caps=Caps.from_spec(SPEC))
    flt = make("tensor_filter", el_name="f", framework="jax-xla",
               model=name)
    ssnk = make("tensor_query_serversink", el_name="qsink", id=server_id)
    sp.add(ssrc, flt, ssnk).link(ssrc, flt, ssnk)

    def make_client(port):
        cp = Pipeline(name=f"dccli-{tag}")
        src = AppSrc(name="src", spec=SPEC)
        cli = make("tensor_query_client", el_name="cli",
                   host="localhost", port=port, connect_type="tcp",
                   timeout=30000, **(client_kw or {}))
        snk = AppSink(name="out")
        cp.add(src, cli, snk).link(src, cli, snk)
        return cp, src, snk, cli

    return sp, ssrc, make_client


class TestDeviceChannel:
    def test_wire_devch_roundtrip_and_forward_compat(self):
        desc = {"fp": "abc/cpux8", "slot": "abc-1", "nbytes": 16}
        m = EdgeMessage(mtype=MSG_QUERY, seq=5, info="x")
        m.devch = desc
        m2 = EdgeMessage.unpack(m.pack())
        assert m2.devch == desc and m2.payloads == []
        # trace + devch coexist in the extension area
        m.trace = {"id": "t-1"}
        m3 = EdgeMessage.unpack(m.pack())
        assert m3.devch == desc and m3.trace == {"id": "t-1"}

    def test_deposit_take_and_miss(self):
        buf = Buffer.of(jnp.arange(4, dtype=jnp.float32), pts=7)
        desc = devch.deposit_buffer(buf)
        assert desc["fp"] == devch.fingerprint()
        got = devch.take_buffer(desc)
        assert got is not None and got.pts == 7
        assert got.residency == "device"
        # second take: slot already redeemed
        assert devch.take_buffer(desc) is None
        # foreign fingerprint: refused
        desc2 = devch.deposit_buffer(buf)
        desc2 = dict(desc2, fp="other-process/cpux8")
        assert devch.take_buffer(desc2) is None
        s = devch.stats()
        assert s["deposits"] == 2 and s["takes"] == 1 \
            and s["misses"] == 2

    def test_to_wire_control_only_when_eligible(self):
        buf = Buffer.of(jnp.arange(4, dtype=jnp.float32))
        data = _to_wire(Envelope(MSG_QUERY, seq=1, buffer=buf),
                        devch=True)
        env = _from_wire(data)
        assert env.buffer is not None
        assert env.buffer.residency == "device"
        # control frame: smaller than the payload framing of the same
        # buffer (no payload table, no MetaInfo headers)
        assert len(data) < len(_to_wire(
            Envelope(MSG_QUERY, seq=1, buffer=env.buffer), devch=False))
        # host frames fall back to payload framing even on a capable conn
        hbuf = Buffer.of(np.arange(4, dtype=np.float32))
        data2 = _to_wire(Envelope(MSG_QUERY, seq=2, buffer=hbuf),
                         devch=True)
        env2 = _from_wire(data2)
        assert env2.buffer is not None
        np.testing.assert_array_equal(env2.buffer.tensors[0].np(),
                                      np.arange(4, dtype=np.float32))
        assert devch.stats()["deposits"] == 1  # only the device frame

    def test_query_roundtrip_zero_crossings(self):
        """same-process TCP offload: after the handshake, request AND
        reply ride the device channel — frames stay in HBM, the ledger
        records no crossing for the streamed frames."""
        sp, ssrc, make_client = _query_rig("fast", 61)
        with sp:
            cp, src, snk, cli = make_client(ssrc.port)
            with cp:
                # warmup (XLA compile) with one host frame
                src.push_buffer(Buffer.of(np.zeros((1, 4), np.float32)))
                assert snk.pull(timeout=30) is not None
                devch.reset()
                xfer.LEDGER.clear()
                n = 6
                for i in range(n):
                    src.push_buffer(Buffer.of(jnp.full((1, 4), float(i))))
                src.end_of_stream()
                assert cp.wait_eos(timeout=30)
                out = _drain(snk)
                assert cli._conn.devch_ok
        assert len(out) == n
        for i, b in enumerate(out):
            assert b.residency == "device"
        s = devch.stats()
        assert s["deposits"] == 2 * n and s["takes"] == 2 * n, s
        assert xfer.LEDGER.totals(direction="h2d")[0] == 0
        assert xfer.LEDGER.totals(direction="d2h")[0] == 0
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_fallback_when_no_shared_mesh(self, monkeypatch):
        """endpoints that do NOT share a device world (fingerprint
        mismatch — e.g. a true cross-host link) transparently stay on
        TCP payload framing: same answers, no channel traffic."""
        import nnstreamer_tpu.edge.transport as transport_mod

        real_ok = devch.handshake_ok
        monkeypatch.setattr(
            transport_mod._devch, "handshake_ok", lambda fp: False)
        try:
            sp, ssrc, make_client = _query_rig("fb", 62)
            with sp:
                cp, src, snk, cli = make_client(ssrc.port)
                with cp:
                    src.push_buffer(Buffer.of(
                        np.zeros((1, 4), np.float32)))
                    assert snk.pull(timeout=30) is not None
                    devch.reset()
                    for i in range(3):
                        src.push_buffer(Buffer.of(
                            jnp.full((1, 4), float(i))))
                    src.end_of_stream()
                    assert cp.wait_eos(timeout=30)
                    out = _drain(snk)
                    assert not cli._conn.devch_ok
        finally:
            monkeypatch.setattr(transport_mod._devch, "handshake_ok",
                                real_ok)
        assert len(out) == 3
        s = devch.stats()
        assert s["deposits"] == 0 and s["takes"] == 0, s
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_opt_out_prop_disables_probe(self):
        sp, ssrc, make_client = _query_rig(
            "opt", 63, client_kw={"device_channel": False})
        with sp:
            cp, src, snk, cli = make_client(ssrc.port)
            with cp:
                src.push_buffer(Buffer.of(jnp.ones((1, 4), jnp.float32)))
                b = snk.pull(timeout=30)
                assert b is not None
                assert not cli._conn.devch_ok
                src.end_of_stream()
                assert cp.wait_eos(timeout=30)
        assert devch.stats()["deposits"] == 0

    def test_edge_pubsub_devch(self):
        """edgesink → edgesrc over localhost TCP: published device
        frames stay in HBM (control frames on the socket)."""
        pub = Pipeline(name="dc-pub")
        psrc = AppSrc(name="src", spec=SPEC)
        esink = make("edgesink", el_name="esink", host="localhost",
                     port=0, connect_type="tcp", topic="t")
        pub.add(psrc, esink).link(psrc, esink)
        with pub:
            port = esink.port
            sub = Pipeline(name="dc-sub")
            esrc = make("edgesrc", el_name="esrc", dest_host="localhost",
                        dest_port=port, connect_type="tcp", topic="t",
                        caps=Caps.from_spec(SPEC), num_buffers=4)
            ssnk = AppSink(name="out")
            sub.add(esrc, ssnk).link(esrc, ssnk)
            with sub:
                time.sleep(0.3)  # subscription + handshake settle
                devch.reset()
                for i in range(4):
                    psrc.push_buffer(Buffer.of(
                        jnp.full((1, 4), float(i))))
                out = []
                deadline = time.monotonic() + 20
                while len(out) < 4 and time.monotonic() < deadline:
                    b = ssnk.pull(timeout=0.5)
                    if b is not None:
                        out.append(b)
        assert len(out) == 4
        for b in out:
            assert b.residency == "device"
        s = devch.stats()
        assert s["deposits"] == 4 and s["takes"] == 4, s

    def test_eviction_bounds_leaked_slots_per_channel(self):
        buf = Buffer.of(jnp.zeros((2,), jnp.float32))
        # a healthy link's single in-flight frame, parked FIRST
        healthy = devch.deposit_buffer(buf, chan="healthy-link")
        descs = [devch.deposit_buffer(buf, chan="stalled-link")
                 for _ in range(devch.MAX_SLOTS + 10)]
        s = devch.stats()
        assert s["parked"] == devch.MAX_SLOTS + 1
        assert s["evicted"] == 10
        # eviction is per channel: the stalled link's oldest slots
        # miss, the newest redeem — and the OTHER link's older frame
        # was never touched by the stalled link's backlog
        assert devch.take_buffer(descs[0]) is None
        assert devch.take_buffer(descs[-1]) is not None
        assert devch.take_buffer(healthy) is not None
        # a closed connection frees its remaining parked frames
        devch.release_chan("stalled-link")
        assert devch.stats()["parked"] == 0

"""Layered configuration system.

Parity target: /root/reference/gst/nnstreamer/nnstreamer_conf.c:47-70 —
env vars override an ini file which overrides compiled-in defaults, plus
free-form custom keys (``nnsconf_get_custom_value_*``).

Layers (highest priority first):
1. environment: ``NNS_TPU_<SECTION>_<KEY>`` (e.g. ``NNS_TPU_COMMON_PLUGINS``)
2. ini file at ``$NNS_TPU_CONF_FILE`` or ``~/.config/nnstreamer_tpu.ini``
3. built-in defaults
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

_DEFAULTS: Dict[str, Dict[str, str]] = {
    "common": {
        "plugins": "",               # extra plugin modules, ':'-separated
        "enable_envvar": "true",
    },
    "filter": {
        # framework priority per model extension (parity:
        # framework_priority_tflite etc., nnstreamer.ini.in)
        "framework_priority_stablehlo": "jax-xla",
        "framework_priority_msgpack": "jax-xla",
        "framework_priority_pkl": "jax-xla",
        "framework_priority_py": "python3",
    },
    "element": {
        "restriction": "",           # allowlist, ':'-separated; empty = all
    },
}


class Conf:
    def __init__(self, path: Optional[str] = None):
        self._cp = configparser.ConfigParser()
        for sec, kv in _DEFAULTS.items():
            self._cp[sec] = dict(kv)
        path = path or os.environ.get("NNS_TPU_CONF_FILE") or os.path.expanduser(
            "~/.config/nnstreamer_tpu.ini")
        self.path = path
        if path and os.path.isfile(path):
            self._cp.read(path)

    def get(self, section: str, key: str, default: str = "") -> str:
        if self._env_enabled() or (section, key) == ("common", "enable_envvar"):
            env = os.environ.get(f"NNS_TPU_{section.upper()}_{key.upper()}")
            if env is not None:
                return env
        try:
            return self._cp.get(section, key)
        except (configparser.NoSectionError, configparser.NoOptionError):
            return default

    def _env_enabled(self) -> bool:
        try:
            v = self._cp.get("common", "enable_envvar")
        except (configparser.NoSectionError, configparser.NoOptionError):
            v = "true"
        v = os.environ.get("NNS_TPU_COMMON_ENABLE_ENVVAR", v)
        return v.strip().lower() in ("1", "true", "yes", "on")

    def get_bool(self, section: str, key: str, default: bool = False) -> bool:
        v = self.get(section, key, "")
        if not v:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    @property
    def extra_plugin_modules(self) -> List[str]:
        v = self.get("common", "plugins", "")
        return [m for m in v.split(":") if m.strip()]

    @property
    def element_restriction(self) -> Optional[List[str]]:
        v = self.get("element", "restriction", "")
        items = [m for m in v.split(":") if m.strip()]
        return items or None

    def framework_priority(self, ext: str) -> List[str]:
        v = self.get("filter", f"framework_priority_{ext.lstrip('.')}", "")
        return [m for m in v.split(",") if m.strip()]


_conf: Optional[Conf] = None
_conf_lock = threading.Lock()


def get_conf(reload: bool = False) -> Conf:
    global _conf
    with _conf_lock:
        if _conf is None or reload:
            _conf = Conf()
        return _conf

#!/usr/bin/env python
"""``nns-ctl`` — closed-loop controller / actuator CLI (see
``nnstreamer_tpu/obs/control.py``; console script ``nns-ctl``)."""

import os
import sys

try:
    import nnstreamer_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from nnstreamer_tpu.obs.control import main

if __name__ == "__main__":
    sys.exit(main())

"""Tensor core (L1): type system, specs, caps, buffers, meta headers."""

from .types import (
    DType,
    MediaType,
    TensorFormat,
    TensorLayout,
    MIMETYPE_TENSOR,
    MIMETYPE_TENSORS,
    TENSOR_COUNT_LIMIT,
    TENSOR_RANK_LIMIT,
    dtype_range,
)
from .spec import (
    TensorSpec,
    TensorsSpec,
    dims_equal,
    dims_to_shape,
    format_dimension,
    parse_dimension,
    shape_to_dims,
)
from .meta import MetaInfo, header_size, META_MAGIC, META_VERSION
from .buffer import (
    Buffer,
    DonatedTensorError,
    Tensor,
    sparse_from_dense,
    sparse_to_dense,
    SECOND,
    MSECOND,
    USECOND,
)
from .caps import ANY, Caps, CapsStruct, Range

__all__ = [
    "DType", "MediaType", "TensorFormat", "TensorLayout",
    "MIMETYPE_TENSOR", "MIMETYPE_TENSORS",
    "TENSOR_COUNT_LIMIT", "TENSOR_RANK_LIMIT", "dtype_range",
    "TensorSpec", "TensorsSpec", "dims_equal", "dims_to_shape",
    "format_dimension", "parse_dimension", "shape_to_dims",
    "MetaInfo", "header_size", "META_MAGIC", "META_VERSION",
    "Buffer", "DonatedTensorError", "Tensor",
    "sparse_from_dense", "sparse_to_dense",
    "SECOND", "MSECOND", "USECOND",
    "ANY", "Caps", "CapsStruct", "Range",
]

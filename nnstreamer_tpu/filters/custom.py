"""Custom filter adapters: ``custom-easy`` and ``python3``.

Parity targets:
- custom-easy: in-process registration of a callback as a model,
  ``NNS_custom_easy_register``
  (/root/reference/gst/nnstreamer/include/tensor_filter_custom_easy.h:56-66,
  tensor_filter_custom_easy.c).
- python3: a user script defining class ``CustomFilter`` with
  ``invoke/getInputDim/getOutputDim/setInputDim``
  (/root/reference/ext/nnstreamer/tensor_filter/tensor_filter_python3.cc:265-301).

These run host-side (numpy) — they are escape hatches, not the TPU hot path;
the scaffold fixtures in tests (passthrough/scaler/average) mirror the
reference's load-bearing test backends
(/root/reference/tests/nnstreamer_example/).
"""

from __future__ import annotations

import importlib.util
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import TensorsSpec
from .api import FilterError, FilterProps, FilterSubplugin
from .registry import register_filter

# -- custom-easy -------------------------------------------------------------

_easy_models: Dict[str, Tuple[Callable, TensorsSpec, TensorsSpec]] = {}
_easy_lock = threading.Lock()


def register_custom_easy(name: str, fn: Callable,
                         in_spec: TensorsSpec, out_spec: TensorsSpec) -> str:
    """Register ``fn(list[np.ndarray]) -> list[np.ndarray]`` as a model."""
    with _easy_lock:
        _easy_models[name] = (fn, in_spec, out_spec)
    return name


def unregister_custom_easy(name: str) -> None:
    with _easy_lock:
        _easy_models.pop(name, None)


def easy_model_registered(name: str) -> bool:
    with _easy_lock:
        return name in _easy_models


@register_filter
class CustomEasyFilter(FilterSubplugin):
    NAME = "custom-easy"
    ACCELERATORS = ("cpu",)
    ALLOCATE_IN_INVOKE = True

    def __init__(self):
        super().__init__()
        self._fn = None
        self._in_spec = None
        self._out_spec = None

    def configure(self, props: FilterProps) -> None:
        super().configure(props)
        model = props.model
        if callable(model):
            self._fn = model
            self._in_spec = props.input_spec
            self._out_spec = props.output_spec
            if self._in_spec is None or self._out_spec is None:
                raise FilterError(
                    "custom-easy: callable model needs input_spec and "
                    "output_spec")
            return
        with _easy_lock:
            entry = _easy_models.get(model)
        if entry is None:
            raise FilterError(f"custom-easy: no registered model {model!r}")
        self._fn, self._in_spec, self._out_spec = entry

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        return self._in_spec, self._out_spec

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        host = [np.asarray(x) for x in inputs]
        out = self._fn(host)
        if not isinstance(out, (list, tuple)):
            out = [out]
        return list(out)


@register_filter
class CustomFilter(CustomEasyFilter):
    """``framework=custom`` — name alias of the callable-model path.

    Parity: the reference's framework="custom" loads a user .so with the
    NNStreamer_custom vtable (tensor_filter_custom.c); on this stack a
    user "native" filter IS a python callable / registered model, so
    both names resolve to the same adapter."""

    NAME = "custom"


# -- python3 -----------------------------------------------------------------


@register_filter
class Python3Filter(FilterSubplugin):
    """Load a user .py file whose ``CustomFilter`` class implements
    ``invoke(list[np.ndarray])`` and declares I/O specs via
    ``getInputDim/getOutputDim`` (returning TensorsSpec or
    (dims-string, types-string)) — optionally ``setInputDim`` for reshape."""

    NAME = "python3"
    ACCELERATORS = ("cpu",)
    ALLOCATE_IN_INVOKE = True

    def __init__(self):
        super().__init__()
        self._obj = None

    def configure(self, props: FilterProps) -> None:
        super().configure(props)
        path = props.model
        if not isinstance(path, str) or not os.path.isfile(path):
            raise FilterError(f"python3: model script not found: {path!r}")
        spec = importlib.util.spec_from_file_location(
            f"nns_tpu_py_filter_{abs(hash(path))}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        cls = getattr(mod, "CustomFilter", None)
        if cls is None:
            raise FilterError(f"python3: {path} defines no CustomFilter class")
        self._obj = cls(*([] if not props.custom else [props.custom]))

    def _spec_of(self, raw) -> TensorsSpec:
        if isinstance(raw, TensorsSpec):
            return raw
        if isinstance(raw, (list, tuple)) and raw and \
                isinstance(raw[0], (list, tuple)):
            # list of per-tensor (dims, dtype) pairs — the reference
            # script style (nns.TensorShape analogs)
            import numpy as np

            from ..core import DType, TensorSpec

            tensors = []
            for dims, dt in raw:
                dt = DType.from_np(np.dtype(dt)) if not isinstance(dt, DType) \
                    else dt
                if isinstance(dims, str):
                    tensors.append(TensorSpec.parse(dims, str(dt)))
                else:
                    tensors.append(TensorSpec(dtype=dt, dims=tuple(dims)))
            return TensorsSpec.of(*tensors)
        dims, types = raw
        return TensorsSpec.parse(dims, types)

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        return (self._spec_of(self._obj.getInputDim()),
                self._spec_of(self._obj.getOutputDim()))

    def set_input_info(self, in_spec: TensorsSpec
                       ) -> Tuple[TensorsSpec, TensorsSpec]:
        if not hasattr(self._obj, "setInputDim"):
            return super().set_input_info(in_spec)
        out = self._obj.setInputDim(in_spec)
        return in_spec, self._spec_of(out)

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        out = self._obj.invoke([np.asarray(x) for x in inputs])
        if not isinstance(out, (list, tuple)):
            out = [out]
        return list(out)

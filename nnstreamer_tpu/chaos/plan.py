"""Deterministic fault injection: the seeded :class:`FaultPlan`.

The reference stack is built for lossy edge deployments (QoS events,
``tensor_query`` timeout/drop semantics, MQTT reconnect-to-alternates)
— this module makes those failure modes *reproducible* so the recovery
machinery can be proven instead of hoped for.  A plan is a seeded RNG
plus a list of :class:`FaultSpec` clauses; three seams consult it:

- **wire** — the edge transports (:mod:`nnstreamer_tpu.edge.transport`)
  pass every framed message through :meth:`FaultPlan.wire`, which can
  drop, delay, duplicate, reorder (swap with the next frame), corrupt,
  force a disconnect, or open a two-sided partition window;
- **invoke** — the model dispatch (``runtime/serving.py`` pool dispatch
  and the ``tensor_filter`` chain/micro-batch paths) asks
  :meth:`FaultPlan.invoke_fault` for ``slow-invoke`` (added device
  latency) / ``fail-invoke`` (a raised :class:`ChaosInvokeError`);
- **queue** — the batching window (``runtime/batching.py``) asks
  :meth:`FaultPlan.queue_stall` for an artificial dispatch stall, which
  shows up upstream as queue pressure.

Every injected fault is counted — locally (:meth:`FaultPlan.counts`)
and in the process metrics registry (``nns_chaos_injected_total``
labeled by fault and seam) — so a soak run can assert "N faults went in
AND every one is accounted for": zero silent drops.

Spec grammar (the ``NNS_TPU_CHAOS`` env var and the ``chaos=`` element
properties share it)::

    [seed=N;]fault[:key=val[,key=val...]][;fault...]

e.g. ``seed=42;drop:p=0.05;delay:ms=40,p=0.2,match=qcli`` or the
deterministic ``disconnect:every=50`` (every 50th frame).  Keys:

``p``      probability per event (default 1; ignored when ``every`` set)
``every``  deterministic cadence: fire on every Nth matching event
``after``  skip the first N matching events
``count``  stop after N injections (0 = unlimited)
``ms``     duration: delay/slow-invoke/queue-pressure sleep, or the
           partition window length (default 50)
``match``  substring of the seam label (link/element/pool name);
           empty matches everything
``dir``    wire faults only: ``tx``/``rx`` (default: both)
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: wire-seam faults (transport framing layer)
WIRE_FAULTS = ("drop", "delay", "duplicate", "reorder", "corrupt",
               "disconnect", "partition")
#: model-path faults (ModelPool / tensor_filter dispatch)
INVOKE_FAULTS = ("slow-invoke", "fail-invoke")
#: batching-window faults (queue pressure)
QUEUE_FAULTS = ("queue-pressure",)

FAULTS = WIRE_FAULTS + INVOKE_FAULTS + QUEUE_FAULTS

_SEAM_OF = {**{f: "wire" for f in WIRE_FAULTS},
            **{f: "invoke" for f in INVOKE_FAULTS},
            **{f: "queue" for f in QUEUE_FAULTS}}


class ChaosInvokeError(RuntimeError):
    """The injected ``fail-invoke`` fault: raised from the model
    dispatch so it rides the SAME error paths a real XLA failure would
    (SharedBatcher ``_error_all`` fan-out, per-owner bus routing)."""


@dataclasses.dataclass
class FaultSpec:
    """One clause of a plan: what to inject, where, how often."""

    fault: str
    p: float = 1.0
    every: int = 0          # deterministic cadence (overrides p)
    after: int = 0          # skip the first N matching events
    count: int = 0          # max injections (0 = unlimited)
    ms: float = 50.0        # delay/stall/partition duration
    match: str = ""         # substring of the seam label
    direction: str = ""     # wire: "tx"/"rx"/"" (both)

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(
                f"unknown fault {self.fault!r}; one of {list(FAULTS)}")
        if not 0.0 <= float(self.p) <= 1.0:
            raise ValueError(f"{self.fault}: p={self.p} not in [0,1]")
        if self.direction not in ("", "tx", "rx"):
            raise ValueError(
                f"{self.fault}: dir={self.direction!r} not tx/rx")
        for key in ("ms", "every", "after", "count"):
            v = getattr(self, key)
            if v < 0:
                # reject at parse time: a negative ms would otherwise
                # blow up as time.sleep(-x) deep in a dispatch path
                raise ValueError(f"{self.fault}: {key}={v} must be >= 0")

    @property
    def seam(self) -> str:
        return _SEAM_OF[self.fault]

    @classmethod
    def parse(cls, clause: str) -> "FaultSpec":
        fault, _, rest = clause.strip().partition(":")
        kw: Dict[str, object] = {}
        for tok in rest.split(","):
            tok = tok.strip()
            if not tok:
                continue
            k, eq, v = tok.partition("=")
            if not eq:
                raise ValueError(f"{clause!r}: expected key=val, "
                                 f"got {tok!r}")
            k = k.strip()
            v = v.strip()
            if k in ("p", "ms"):
                kw[k] = float(v)
            elif k in ("every", "after", "count"):
                kw[k] = int(v)
            elif k == "match":
                kw[k] = v
            elif k == "dir":
                kw["direction"] = v
            else:
                raise ValueError(f"{clause!r}: unknown key {k!r}")
        return cls(fault=fault.strip(), **kw)


class _SpecState:
    """Per-spec runtime state (under the plan lock): how many events it
    saw, how many times it fired, the reorder hold slot."""

    __slots__ = ("seen", "fired")

    def __init__(self):
        self.seen = 0
        self.fired = 0


@dataclasses.dataclass
class WireOp:
    """What the transport must do with one framed message:
    ``frames`` replaces the single original frame (empty = drop/hold,
    two entries = duplicate or a released reorder pair), ``delay_s`` is
    slept before sending/delivering, ``disconnect`` closes the
    connection after the frames go out."""

    frames: List[bytes]
    delay_s: float = 0.0
    disconnect: bool = False


class FaultPlan:
    """A seeded, thread-safe fault schedule.  Install process-wide with
    :func:`nnstreamer_tpu.chaos.install_plan` or attach to a single
    element via its ``chaos=`` property."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        import random

        self.specs = list(specs)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._state = [_SpecState() for _ in self.specs]
        self._counts: Dict[Tuple[str, str], int] = {}
        # reorder hold slots: (label, direction) -> held frame bytes
        self._held: Dict[Tuple[str, str], bytes] = {}
        # partition window: until this monotonic instant, every matching
        # wire frame (both directions) is dropped
        self._partition_until = 0.0
        self._partition_match = ""
        self._metric = None  # lazily bound nns_chaos_injected_total

    # -- construction ---------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Build a plan from the shared grammar (see module doc)."""
        seed = 0
        clauses: List[FaultSpec] = []
        for part in str(spec).split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[5:])
                continue
            clauses.append(FaultSpec.parse(part))
        if not clauses:
            raise ValueError(f"chaos spec {spec!r} names no faults")
        return cls(clauses, seed=seed)

    # -- bookkeeping ----------------------------------------------------------

    def _record(self, spec: FaultSpec) -> None:
        key = (spec.fault, spec.seam)
        self._counts[key] = self._counts.get(key, 0) + 1
        metric = self._metric
        if metric is None:
            from ..obs.metrics import REGISTRY

            metric = self._metric = REGISTRY.counter(
                "nns_chaos_injected_total",
                "faults injected by the active chaos plan",
                labelnames=("fault", "seam"))
        metric.labels(fault=spec.fault, seam=spec.seam).inc()

    def counts(self) -> Dict[str, int]:
        """``fault -> injections`` so far (all seams merged)."""
        with self._lock:
            out: Dict[str, int] = {}
            for (fault, _seam), n in self._counts.items():
                out[fault] = out.get(fault, 0) + n
            return out

    @property
    def total_injected(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def _fires(self, i: int, spec: FaultSpec, label: str,
               direction: str = "") -> bool:
        """Whether spec ``i`` fires for this event (caller holds the
        lock).  Deterministic under one seed: the RNG is consulted in
        event order, and ``every=`` clauses skip it entirely."""
        if spec.match and spec.match not in label:
            return False
        if spec.direction and direction and spec.direction != direction:
            return False
        st = self._state[i]
        st.seen += 1
        if st.seen <= spec.after:
            return False
        if spec.count and st.fired >= spec.count:
            return False
        if spec.every > 0:
            fire = (st.seen - spec.after) % spec.every == 0
        else:
            fire = spec.p >= 1.0 or self._rng.random() < spec.p
        if fire:
            st.fired += 1
        return fire

    # -- wire seam ------------------------------------------------------------

    def wire(self, label: str, direction: str,
             data: bytes) -> Optional[WireOp]:
        """Pass one framed message through the plan.  Returns ``None``
        when untouched (the common case — callers skip all bookkeeping),
        else a :class:`WireOp` to apply."""
        op: Optional[WireOp] = None
        with self._lock:
            now = time.monotonic()
            if self._partition_until > now and \
                    (not self._partition_match
                     or self._partition_match in label):
                # inside a partition window: everything matching is lost
                # (both directions — a real partition has no half-open
                # side at this layer)
                return WireOp(frames=[])
            for i, spec in enumerate(self.specs):
                if spec.seam != "wire":
                    continue
                if spec.fault == "corrupt" and \
                        not isinstance(data, (bytes, bytearray)):
                    continue  # inproc frames are object references:
                    # there are no wire bytes to corrupt
                if not self._fires(i, spec, label, direction):
                    continue
                self._record(spec)
                if op is None:
                    op = WireOp(frames=[data])
                if spec.fault == "drop":
                    op.frames = []
                elif spec.fault == "delay":
                    op.delay_s += spec.ms / 1e3
                elif spec.fault == "duplicate":
                    op.frames = op.frames + op.frames
                elif spec.fault == "corrupt":
                    op.frames = [self._corrupt(f) for f in op.frames]
                elif spec.fault == "disconnect":
                    op.disconnect = True
                elif spec.fault == "partition":
                    self._partition_until = now + spec.ms / 1e3
                    self._partition_match = spec.match
                    op.frames = []
                elif spec.fault == "reorder":
                    # pairwise swap-with-next: with nothing held, hold
                    # the last live frame; with a frame already held,
                    # release it AFTER the current frames.  Operates on
                    # op.frames (not the original data) so composition
                    # stays sound: a frame another clause dropped is
                    # never resurrected, and a duplicate's second copy
                    # is held, not lost.
                    key = (label, direction)
                    held = self._held.pop(key, None)
                    if held is not None:
                        op.frames = op.frames + [held]
                    elif op.frames:
                        self._held[key] = op.frames[-1]
                        op.frames = op.frames[:-1]
        return op

    def flush_held(self, label: str, direction: str) -> Optional[bytes]:
        """Release a reorder hold slot.  A hold that is never released
        (stream ended right after it) degenerates into a drop — which
        is realistic network behavior, and the RECEIVER-side accounting
        (timeouts, EOS drain) covers it exactly like a real drop; the
        injection was already counted as ``reorder``."""
        with self._lock:
            return self._held.pop((label, direction), None)

    def _corrupt(self, data: bytes) -> bytes:
        """Flip one byte at a seeded offset — enough for the wire
        codec's header/length checks to reject the frame."""
        if not data:
            return data
        buf = bytearray(data)
        i = self._rng.randrange(len(buf))
        buf[i] ^= 0xFF
        return bytes(buf)

    # -- invoke seam ----------------------------------------------------------

    def invoke_fault(self, label: str) -> Optional[Tuple[str, float]]:
        """Model-dispatch fault for one window/frame: ``("slow", s)``
        to sleep before the dispatch, or ``("fail", 0.0)`` — callers
        raise :class:`ChaosInvokeError`.  ``fail`` wins when both
        fire (the sleep would only delay the error)."""
        out: Optional[Tuple[str, float]] = None
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.seam != "invoke":
                    continue
                if not self._fires(i, spec, label):
                    continue
                self._record(spec)
                if spec.fault == "fail-invoke":
                    out = ("fail", 0.0)
                elif out is None:
                    out = ("slow", spec.ms / 1e3)
        return out

    # -- queue seam -----------------------------------------------------------

    def queue_stall(self, label: str) -> float:
        """Seconds to stall a batching-window flush (0 = none): the
        injected device slowdown that turns into upstream queue
        pressure."""
        stall = 0.0
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.seam != "queue":
                    continue
                if not self._fires(i, spec, label):
                    continue
                self._record(spec)
                stall += spec.ms / 1e3
        return stall

    def __repr__(self):
        cl = ";".join(s.fault for s in self.specs)
        return f"<FaultPlan seed={self.seed} [{cl}]>"


def apply_wire_op(op: WireOp, deliver: Callable[[Any], Any],
                  disconnect: Optional[Callable[[], None]] = None) -> bool:
    """The one implementation of applying a :class:`WireOp`: sleep the
    delay, deliver each frame, then run the disconnect action.  Every
    transport seam routes through here so the op semantics (and any
    future fix to them) live in one place.  Returns False when any
    ``deliver`` explicitly returned False (tx sites report send
    failures; rx sites return None, which counts as success)."""
    if op.delay_s > 0:
        time.sleep(op.delay_s)
    ok = True
    for f in op.frames:
        ok = (deliver(f) is not False) and ok
    if op.disconnect and disconnect is not None:
        disconnect()
    return ok


def apply_invoke_fault(plan: "FaultPlan", label: str) -> None:
    """Convenience for the dispatch sites: sleep a ``slow-invoke`` /
    raise a ``fail-invoke`` (the raise rides the caller's normal error
    path — bus routing, SharedBatcher fan-out)."""
    fault = plan.invoke_fault(label)
    if fault is None:
        return
    kind, s = fault
    if kind == "fail":
        raise ChaosInvokeError(f"injected fail-invoke at {label}")
    time.sleep(s)

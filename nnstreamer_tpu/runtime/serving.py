"""Shared-model serving runtime: cross-pipeline batch coalescing.

PR 2's :class:`~nnstreamer_tpu.runtime.batching.MicroBatcher` coalesces
the in-flight buffers of ONE ``tensor_filter``.  At serving scale that
is the wrong granularity: 100 concurrent pipelines running the same
jax-xla model mean 100 params copies in HBM, 100 per-bucket executable
caches, and 100 independent batch windows that each dispatch
nearly-empty buckets.  Continuous-batching servers (Orca, OSDI '22) and
prediction-serving systems that share one model replica across request
streams (Clipper, NSDI '17) coalesce at the MODEL, not the element.

This module lifts the window machinery to per-model:

- :class:`ModelPool` — a process-wide table of opened sub-plugin
  instances, ref-counted and keyed by ``(framework, model,
  accelerator/mesh config)``.  N filters with ``share-model=true``
  referencing the same model share ONE instance: one params copy, one
  per-bucket executable cache (``filters/jax_xla.py`` ``open_shared`` /
  ``close_shared`` back this at the framework level).
- :class:`PoolEntry` — one pooled model plus its cross-stream batcher
  and :class:`~nnstreamer_tpu.utils.stats.InvokeStats` (dispatches,
  frames, and *distinct streams per dispatch*).
- :class:`SharedBatcher` — a MicroBatcher over ``(stream, buffer)``
  pairs from MANY pipelines.  Per-stream FIFO order is preserved (one
  FIFO window, serialized flushes); results are demuxed back to each
  owning filter's downstream pad on that filter's flush context (a
  broken downstream in pipeline A errors on A's bus without killing
  B's demux); per-stream EOS flushes only that stream's parked frames;
  and the **adaptive window** flushes early whenever the device is idle
  instead of always waiting out the deadline — coalescing happens
  exactly while a dispatch is in flight, so an idle device never sits
  out a ``batch-timeout-ms``.

Frameworks without ``SUPPORTS_BATCH`` still share the instance (one
params copy); their streams fall back to per-frame dispatch through the
element's normal chain path — no frames are parked, none are lost.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.stats import InvokeStats
from .batching import MicroBatcher, parse_buckets, pick_bucket

#: sampling cadence of pool-level dispatch stats (same policy as
#: TensorFilter.STAT_SAMPLE_INTERVAL: at most one blocking sample per
#: interval, so stats never throttle the shared hot path)
POOL_STAT_SAMPLE_INTERVAL = 1.0


def block_all(outs) -> None:
    """Block until every array in ``outs`` finished executing on the
    device (arrays without ``block_until_ready`` pass through)."""
    for o in outs:
        if hasattr(o, "block_until_ready"):
            o.block_until_ready()


class PoolConflictError(ValueError):
    """Sharers of one pool entry disagree on pool-level settings
    (``batch`` / ``batch-timeout-ms`` / ``batch-buckets`` are properties
    of the SHARED window, not of one element)."""


class SharedBatcher(MicroBatcher):
    """Deadline + max-batch coalescer over ``(stream, item)`` pairs.

    Inherits the MicroBatcher contract — serialized FIFO flushes,
    full/deadline/forced window closes — and adds per-stream draining:
    :meth:`flush_stream` dispatches windows from the head of the FIFO
    until none of one stream's frames are parked, leaving frames other
    streams parked *after* that point untouched.  Runs with the adaptive
    window on by default (idle device ⇒ flush now; busy device ⇒ keep
    coalescing until full/deadline).
    """

    def __init__(self, max_batch: int, timeout_s: float,
                 flush_fn: Callable[[List[Any]], None],
                 error_fn: Optional[Callable[[BaseException], None]] = None,
                 adaptive: bool = True, name: str = ""):
        super().__init__(max_batch, timeout_s, flush_fn, error_fn,
                         adaptive=adaptive, name=name)

    def submit_from(self, stream: Any, item: Any) -> None:
        """Enqueue one frame of ``stream``; dispatches inline when the
        cross-stream window fills."""
        self.submit((stream, item))

    def pending_of(self, stream: Any) -> int:
        with self._cv:
            return sum(1 for s, _ in self._pending if s is stream)

    def flush_stream(self, stream: Any) -> None:
        """Drain windows (FIFO from the head) until no frame of
        ``stream`` is parked — the per-stream EOS/stop path.  Frames of
        other streams that arrived before this stream's last frame ride
        along (order is preserved); frames parked after it stay for
        their own window.  Returns only after any in-flight window that
        may carry this stream's frames completed."""
        while True:
            with self._cv:
                mine = any(s is stream for s, _ in self._pending)
            if not mine:
                break
            if self._drain() == 0:
                break
            self.flushes_forced += 1
        with self._flush_serial_lock:
            pass  # barrier: flushes are FIFO-serialized, so once this
            # lock is free every window taken before now has demuxed


class PoolEntry:
    """One pooled model: the shared sub-plugin instance, the attached
    streams, the cross-stream batcher, and pool-level stats."""

    def __init__(self, pool: "ModelPool", key: Tuple,
                 subplugin: Any, close_fn: Callable[[Any], None]):
        self.pool = pool
        self.key = key
        self.subplugin = subplugin
        self._close_fn = close_fn
        self.refcount = 0  # managed by ModelPool under the pool lock
        self.stats = InvokeStats()
        self._lock = threading.Lock()
        self._streams: Dict[int, Any] = {}  # id(owner) -> owner element
        self.batcher: Optional[SharedBatcher] = None
        self.buckets: Tuple[int, ...] = (1,)
        self._batch_cfg: Optional[Tuple] = None
        # dispatch sampling state (serialized by the batcher flush lock)
        self._seq = 0
        self._last_sample_ts = 0.0
        self._last_out: Any = None
        # sampling cadence: the pool default, tightened by any attached
        # filter's stat-sample-interval-ms (the pool keeps the minimum
        # so the most latency-curious sharer wins)
        self.sample_interval = POOL_STAT_SAMPLE_INTERVAL

    # -- streams -------------------------------------------------------------

    @property
    def attached_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    def attach(self, owner: Any, batch: int, timeout_ms: float,
               buckets_spec: str) -> bool:
        """Register ``owner`` as a live stream of this entry.  The first
        attach fixes the pool-level window settings; later attaches with
        different settings raise :class:`PoolConflictError`.  Returns
        True when the owner must submit through the shared batcher,
        False for shared-instance/per-frame dispatch (``batch<=1`` or a
        framework without ``SUPPORTS_BATCH``)."""
        batch = int(batch or 1)
        batched = batch > 1 and bool(
            getattr(self.subplugin, "SUPPORTS_BATCH", False))
        cfg = (batch, float(timeout_ms), str(buckets_spec or "").strip())
        owner_ms = getattr(owner, "stat_sample_interval_ms", None)
        start = None
        with self._lock:
            if owner_ms is not None:
                self.sample_interval = min(self.sample_interval,
                                           float(owner_ms) / 1e3)
            if self._streams and self._batch_cfg is not None \
                    and cfg != self._batch_cfg:
                raise PoolConflictError(
                    f"{getattr(owner, 'name', owner)}: batch settings "
                    f"{cfg} conflict with the pool's {self._batch_cfg} — "
                    f"batch/batch-timeout-ms/batch-buckets are pool-level "
                    f"for share-model filters and must agree across all "
                    f"{len(self._streams)} sharer(s)")
            self._streams[id(owner)] = owner
            self._batch_cfg = cfg
            if batched and self.batcher is None:
                self.buckets = parse_buckets(cfg[2], batch)
                self.batcher = SharedBatcher(
                    max_batch=batch, timeout_s=cfg[1] / 1e3,
                    flush_fn=self._dispatch, error_fn=self._error_all,
                    name=f"pool:{self.key[0]}")
                start = self.batcher
            n = len(self._streams)
        self.stats.attached_streams = n
        if start is not None:
            start.start()
        return batched

    def detach(self, owner: Any) -> None:
        """Unregister one stream: flush ITS parked frames first (no
        frame loss on a mid-stream stop), then — if it was the last
        stream out — drain and tear the batcher down so a later
        attach can bring new window settings."""
        with self._lock:
            present = self._streams.pop(id(owner), None) is not None
            batcher = self.batcher
            n = len(self._streams)
            last = not self._streams
            if last:
                self.batcher = None
                self._batch_cfg = None
        self.stats.attached_streams = n
        if batcher is None:
            return
        if present and not last:
            batcher.flush_stream(owner)
        elif last:
            batcher.flush()  # nothing can be parked but a survivor's
            # tail; drain everything before the timer dies
            batcher.stop()

    def flush_stream(self, owner: Any) -> None:
        """Per-stream EOS: dispatch this stream's parked frames (other
        streams' windows are untouched past that point)."""
        with self._lock:
            batcher = self.batcher
        if batcher is not None:
            batcher.flush_stream(owner)

    def submit(self, owner: Any, buf: Any) -> None:
        with self._lock:
            batcher = self.batcher
        if batcher is None:
            raise RuntimeError(
                f"{getattr(owner, 'name', owner)}: stream is not "
                f"attached to a shared batcher (start() not run?)")
        batcher.submit_from(owner, buf)

    # -- the cross-stream dispatch -------------------------------------------

    def _dispatch(self, items: List[Tuple[Any, Any]]) -> None:
        """Window flush: ONE invoke for frames from every attached
        stream, then demux each result back to its owner's downstream
        pad.  Serialized by the batcher (never concurrent), FIFO — so
        per-stream order is global arrival order."""
        sp = self.subplugin
        owners: Dict[int, List[Any]] = {}
        for owner, _ in items:
            owners.setdefault(id(owner), [owner, 0])[1] += 1
        self._seq += 1
        now = time.monotonic()
        sample = self._seq == 1 or \
            now - self._last_sample_ts >= self.sample_interval
        if sample and self._last_out is not None:
            # drain the async backlog first, so t0→done times ONE window
            block_all([self._last_out])
        t0 = time.monotonic()
        try:
            # frame prep inside the guard: items already left the
            # pending queue, so ANY failure from here on loses the
            # window and must surface on every owner's bus
            frames = [owner._pool_frame_inputs(buf)
                      for owner, buf in items]
            if getattr(sp, "SUPPORTS_BATCH", False):
                bucket = pick_bucket(len(frames), self.buckets)
                outs = sp.invoke_batched(frames, bucket)
            else:
                # shared instance without a batched entry point: the
                # window still coalesces (ordering, EOS semantics) but
                # each frame dispatches separately
                outs = [sp.invoke(list(f)) for f in frames]
        except Exception as e:  # noqa: BLE001 - a failed shared window
            # affects EVERY stream that parked a frame in it: the error
            # must land on each owner's bus, not only on whichever
            # producer happened to trigger the flush
            for owner, _n in owners.values():
                owner.post_error(e)
            return
        flat = [o for out in outs for o in out]
        if sample:
            block_all(flat)
            self.stats.record(time.monotonic() - t0, frames=len(items),
                              streams=len(owners))
            self._last_sample_ts = time.monotonic()
        else:
            self.stats.count(frames=len(items), streams=len(owners))
        self._last_out = flat[-1] if flat else None
        for owner, n in owners.values():
            owner.invoke_stats.count(frames=n)
        for (owner, buf), out in zip(items, outs):
            try:
                # the owner's flush context: push through ITS pads, so
                # a broken downstream errors on ITS bus only
                owner._pool_emit(buf, out)
            except Exception as e:  # noqa: BLE001 - keep demuxing the
                # other streams' frames of this window
                owner.post_error(e)

    def _error_all(self, err: BaseException) -> None:
        with self._lock:
            owners = list(self._streams.values())
        for o in owners:  # post outside the lock: bus handlers reenter
            o.post_error(err)

    # -- teardown (pool-internal) --------------------------------------------

    def _close(self) -> None:
        batcher, self.batcher = self.batcher, None
        if batcher is not None:
            batcher.flush()
            batcher.stop()
        self._close_fn(self.subplugin)


class ModelPool:
    """Process-wide ref-counted table of opened sub-plugin instances.

    ``acquire`` returns the existing entry for a key (refcount+1) or
    opens a new one via ``open_fn``; ``release`` closes the instance
    when the last reference drops.  Keys must carry everything that
    makes two opens non-interchangeable — the helper :func:`pool_key`
    builds them from FilterProps.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, PoolEntry] = {}

    def acquire(self, key: Tuple, open_fn: Callable[[], Any],
                close_fn: Callable[[Any], None]) -> PoolEntry:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = PoolEntry(self, key, open_fn(), close_fn)
                self._entries[key] = entry
            entry.refcount += 1
            return entry

    def release(self, entry: PoolEntry) -> None:
        close = False
        with self._lock:
            entry.refcount -= 1
            if entry.refcount <= 0:
                self._entries.pop(entry.key, None)
                close = True
        if close:
            entry._close()

    def get(self, key: Tuple) -> Optional[PoolEntry]:
        with self._lock:
            return self._entries.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        """Drop every entry regardless of refcount (test teardown)."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            e._close()


def pool_key(framework: str, props: Any) -> Tuple:
    """Build the ModelPool key from a framework name + FilterProps:
    everything that makes two opens non-interchangeable (model identity,
    placement, custom options, forced I/O specs).  Non-string models
    (callables, ModelDef, lists) key by object identity — two filters
    share only when handed the very same object."""
    model = props.model
    if isinstance(model, (list, tuple)):
        mkey = tuple(m if isinstance(m, str) else f"obj:{id(m)}"
                     for m in model)
    elif isinstance(model, str):
        mkey = model
    else:
        mkey = f"obj:{id(model)}"
    return (str(framework), mkey,
            str(props.accelerator or ""), str(props.custom or ""),
            str(getattr(props, "mesh", "") or ""),
            str(getattr(props, "sharding", "") or ""),
            str(getattr(props, "devices", "") or ""),
            str(props.input_spec or ""), str(props.output_spec or ""),
            str(props.shared_key or ""))


#: the process-wide pool `tensor_filter share-model=true` attaches to
MODEL_POOL = ModelPool()

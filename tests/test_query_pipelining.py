"""tensor_query_client request pipelining, out-of-order completion, and
mid-stream failover.

Parity: the reference client overlaps requests through an async answer
queue while its edge thread keeps receiving
(/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:673-741).
These tests drive the equivalent here: with a server that injects latency
per request, a pipelined client must sustain ≈ max_request requests in
flight (≥4× the serial 1/RTT rate), tolerate replies arriving out of
order, and fail over to an alternate server mid-stream.
"""

import threading
import time

import numpy as np

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.edge import Envelope, MSG_QUERY
from nnstreamer_tpu.edge.transport import InprocServer
from nnstreamer_tpu.edge.wire import MSG_REPLY
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SPEC = TensorsSpec.parse("4:1", "float32")


class DelayServer:
    """Inproc server that answers each query after ``delay`` seconds,
    each on its own timer thread (replies overlap like a pipelined remote
    pipeline's would).  ``strip_seq`` emulates a server pipeline that
    loses the query_seq meta: every reply goes out with seq=0, IN ORDER,
    with per-request delays taken from ``delays``."""

    def __init__(self, host: str, port: int, delay: float,
                 reorder: bool = False, strip_seq: bool = False,
                 delays=None, drop=None):
        self.transport = InprocServer(host, port)
        self.transport.on_message = self._on_message
        self.transport.caps_provider = lambda: ""
        self.delay = delay
        self.reorder = reorder
        self.strip_seq = strip_seq
        self.delays = list(delays or [])
        self.drop = set(drop or ())  # strip_seq: arrival indices to drop
        self.received = 0
        self._pair = []  # reorder: hold one request back, reply in reverse
        self._fifo = []  # strip_seq: strictly ordered reply worker
        self._fifo_cv = threading.Condition()
        self._fifo_thread = None
        self._run = True

    def start(self):
        self.transport.start()
        if self.strip_seq:
            self._fifo_thread = threading.Thread(
                target=self._fifo_loop, daemon=True)
            self._fifo_thread.start()
        return self

    def stop(self):
        self._run = False
        with self._fifo_cv:
            self._fifo_cv.notify_all()
        self.transport.stop()

    def _reply(self, client_id: int, env: Envelope, seq=None):
        out = Buffer.of(env.buffer.tensors[0].np() * 2.0)
        self.transport.send(client_id, Envelope(
            MSG_REPLY, client_id=client_id,
            seq=env.seq if seq is None else seq, buffer=out))

    def _fifo_loop(self):
        k = 0
        while self._run:
            with self._fifo_cv:
                if not self._fifo:
                    self._fifo_cv.wait(timeout=0.1)
                    continue
                cid, env = self._fifo.pop(0)
            d = self.delays[k] if k < len(self.delays) else self.delay
            if k in self.drop:
                k += 1
                continue  # silently drop this query — no reply ever
            k += 1
            time.sleep(d)
            self._reply(cid, env, seq=0)

    def _on_message(self, client_id: int, env: Envelope):
        if env.mtype != MSG_QUERY or env.buffer is None:
            return
        self.received += 1
        if self.strip_seq:
            with self._fifo_cv:
                self._fifo.append((client_id, env))
                self._fifo_cv.notify()
            return
        if self.reorder:
            # reply to pairs in reverse order: (2,1), (4,3), …
            self._pair.append((client_id, env))
            if len(self._pair) == 2:
                pair, self._pair = self._pair, []
                for cid, e in reversed(pair):
                    self._reply(cid, e)
            return
        d = self.delays[self.received - 1] \
            if self.received - 1 < len(self.delays) else self.delay
        t = threading.Timer(d, self._reply, (client_id, env))
        t.daemon = True
        t.start()


def _client(host, port, **kw):
    p = Pipeline(name="qp-client")
    src = AppSrc(name="src", spec=SPEC)
    kw.setdefault("timeout", 10000)
    cli = make("tensor_query_client", el_name="cli", host=host, port=port,
               connect_type="inproc", **kw)
    snk = AppSink(name="out", max_buffers=256)
    p.add(src, cli, snk).link(src, cli, snk)
    return p, src, cli, snk


def _drain(snk):
    out = []
    while True:
        b = snk.pull(timeout=0.3)
        if b is None:
            return out
        out.append(b)


class TestPipelining:
    def test_throughput_beats_serial_by_4x(self):
        delay, n = 0.2, 16
        srv = DelayServer("inproc-qp-thr", 7201, delay).start()
        try:
            p, src, cli, snk = _client("inproc-qp-thr", 7201,
                                       max_request=16)
            with p:
                t0 = time.perf_counter()
                for i in range(n):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                elapsed = time.perf_counter() - t0
                out = _drain(snk)
        finally:
            srv.stop()
        serial = n * delay  # the old send-then-block chain's floor
        assert len(out) == n and cli.dropped == 0
        assert elapsed < serial / 4, \
            f"pipelined run took {elapsed:.2f}s vs serial floor {serial:.2f}s"
        for i, b in enumerate(out):  # stream order and per-seq matching
            assert b.pts == i
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_out_of_order_replies_push_in_stream_order(self):
        srv = DelayServer("inproc-qp-ooo", 7202, 0.0, reorder=True).start()
        try:
            p, src, cli, snk = _client("inproc-qp-ooo", 7202, max_request=8)
            with p:
                for i in range(8):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            srv.stop()
        assert [b.pts for b in out] == list(range(8))
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_seqless_replies_do_not_shift_after_expiry(self):
        """A server that strips query_seq meta (all replies seq=0) pairs
        answers FIFO.  When one request expires, its late reply must be
        absorbed by the expired entry's tombstone — NOT matched to the
        next pending request, which would shift every later answer onto
        the wrong input buffer (review finding, round 3)."""
        # request 0: instant (teaches the client it's in seq-less mode);
        # request 1: 0.9s — expires at the 0.6s client timeout but its
        # late reply lands inside the tombstone's grace window;
        # requests 2..4: pushed after 1 expired, replied right after 1's
        # late reply (FIFO server) — they must pair 2→2, 3→3, 4→4
        srv = DelayServer("inproc-qp-sl", 7205, 0.0, strip_seq=True,
                          delays=[0.0, 0.9, 0.0, 0.0, 0.0]).start()
        try:
            p, src, cli, snk = _client("inproc-qp-sl", 7205,
                                       max_request=8, timeout=600)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                first = snk.pull(timeout=5)
                assert first is not None and first.pts == 0
                src.push_buffer(Buffer.of(
                    np.ones((1, 4), np.float32), pts=1))
                time.sleep(0.7)  # request 1 expires at 0.6s
                for i in range(2, 5):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            srv.stop()
        assert cli.timeouts == 1          # request 1 timed out
        assert [b.pts for b in out] == [2, 3, 4]
        for b in out:                     # every answer on the RIGHT input
            np.testing.assert_array_equal(
                b.tensors[0].np(),
                np.full((1, 4), 2.0 * b.pts, np.float32))

    def test_seqd_late_reply_consumes_tombstone_and_unblocks(self):
        """A tombstoned request's own SEQ'D reply proves the server
        preserves seqs: it must consume the tombstone (and drop the
        ordering machinery) so completed replies parked behind it flush
        immediately instead of waiting out the grace window."""
        # request 1: 0.8s (expires at 0.5s; its OWN seq'd reply arrives
        # at 0.8s while it is the only — tombstoned — entry, so the
        # consume-tombstone branch is what must fire, not the purge on a
        # different reply); request 2 is pushed only afterwards
        srv = DelayServer("inproc-qp-sq", 7211, 0.0,
                          delays=[0.8, 0.0]).start()
        try:
            p, src, cli, snk = _client("inproc-qp-sq", 7211,
                                       max_request=8, timeout=500)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                time.sleep(0.95)  # tombstoned at 0.5s; reply at 0.8s
                with cli._iflock:  # the tombstone was CONSUMED, not
                    assert not cli._inflight  # grace-expired (that would
                # be at ~1.0s) — and exact matching was re-learned
                assert cli._seqless is False
                src.push_buffer(Buffer.of(
                    np.ones((1, 4), np.float32), pts=1))
                t0 = time.monotonic()
                got = snk.pull(timeout=3)
                dt = time.monotonic() - t0
                src.end_of_stream()
                assert p.wait_eos(timeout=10)
        finally:
            srv.stop()
        assert cli.timeouts == 1
        assert got is not None and got.pts == 1
        np.testing.assert_array_equal(
            got.tensors[0].np(), np.full((1, 4), 2.0, np.float32))
        assert dt < 0.5, f"parked {dt:.2f}s behind a consumable tombstone"

    def test_seqless_first_request_expiry_does_not_shift(self):
        """Worst case for FIFO pairing: the VERY FIRST request expires
        before any reply has revealed whether the server preserves seqs.
        Expiry must stay conservative (tombstone) so the late seq-0 reply
        is absorbed instead of pairing with the next request."""
        # request 0: 0.9s (expires at the 0.6s timeout, reply absorbed);
        # requests 1..3: pushed after the expiry, instant FIFO replies
        srv = DelayServer("inproc-qp-sl0", 7208, 0.0, strip_seq=True,
                          delays=[0.9, 0.0, 0.0, 0.0]).start()
        try:
            p, src, cli, snk = _client("inproc-qp-sl0", 7208,
                                       max_request=8, timeout=600)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                time.sleep(0.7)  # request 0 expires with mode unknown
                for i in range(1, 4):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            srv.stop()
        assert cli.timeouts == 1
        assert [b.pts for b in out] == [1, 2, 3]
        for b in out:
            np.testing.assert_array_equal(
                b.tensors[0].np(),
                np.full((1, 4), 2.0 * b.pts, np.float32))

    def test_seqless_multi_timeout_stall_recovers(self):
        """A server stall that expires SEVERAL requests at once: each
        late reply must be absorbed by its own tombstone (no absorb cap),
        so the first post-stall request pairs with its own answer."""
        # requests 1-3 stall 0.9s each start... FIFO worker: delays are
        # per-request sequential, so give request 1 the whole stall
        srv = DelayServer("inproc-qp-stall", 7210, 0.0, strip_seq=True,
                          delays=[0.0, 0.9, 0.0, 0.0, 0.0, 0.0]).start()
        try:
            p, src, cli, snk = _client("inproc-qp-stall", 7210,
                                       max_request=8, timeout=400)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                assert snk.pull(timeout=5).pts == 0   # seqless established
                # 1-3 all in flight during the stall → all expire at 0.4s
                for i in range(1, 4):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                time.sleep(1.2)  # stall ends at 0.9; replies 1-3 absorbed
                src.push_buffer(Buffer.of(
                    np.full((1, 4), 4.0, np.float32), pts=4))
                got = snk.pull(timeout=3)
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
        finally:
            srv.stop()
        assert cli.timeouts == 3
        assert got is not None and got.pts == 4
        np.testing.assert_array_equal(
            got.tensors[0].np(), np.full((1, 4), 8.0, np.float32))

    def test_seqless_server_drop_stays_live(self):
        """A seq-less server that silently DROPS a query skews FIFO
        pairing in a way NO client can repair: the dropped request's
        successor reply arrives while it is still pending and pairs with
        it — exactly the reference's arrival-order semantics
        (tensor_query_client.c answer queue).  The exactness guarantee
        lives in seq'd mode (our serversrc echoes query_seq; see the
        per-seq assertions in the other tests).  What seq-less mode DOES
        guarantee: the stream stays live — every request is accounted
        for as a delivered answer or a visible timeout, no hang, no
        unbounded loss cascade."""
        srv = DelayServer("inproc-qp-drop", 7209, 0.0, strip_seq=True,
                          delays=[0.0], drop=[1]).start()
        try:
            p, src, cli, snk = _client("inproc-qp-drop", 7209,
                                       max_request=8, timeout=500)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                assert snk.pull(timeout=5).pts == 0   # seqless established
                # request 1 is dropped by the server; 2.. keep flowing
                for i in range(1, 10):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                    time.sleep(0.15)
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            srv.stop()
        assert cli.timeouts >= 1          # the drop is visible
        assert len(out) >= 7              # the stream did not cascade
        assert len(out) + cli.timeouts >= 9  # every request accounted for

    def test_failover_resets_resend_deadlines(self):
        """A slow reconnect can outlive the original request deadlines
        (set at enqueue).  The failover resend must restart the clock so
        the resent requests aren't expired as spurious timeouts while the
        new server redoes the work (review finding, round 3)."""
        a = DelayServer("inproc-qp-fd-a", 7206, 30.0).start()  # never answers
        # B answers in 0.45s — later than the aged deadlines below, so
        # without the deadline reset the resends expire before B replies
        b = DelayServer("inproc-qp-fd-b", 7207, 0.45).start()
        try:
            p, src, cli, snk = _client(
                "inproc-qp-fd-a", 7206, max_request=8, timeout=800,
                alternate_hosts="inproc-qp-fd-b:7207")
            with p:
                for i in range(3):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                time.sleep(0.1)  # let the requests reach server A
                # simulate a reconnect that consumed most of the timeout:
                # age the deadlines so they outlive the failover (~0.2s)
                # but not B's 0.45s service time
                with cli._iflock:
                    for ent in cli._inflight.values():
                        ent[2] = time.monotonic() + 0.5
                a.stop()
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            b.stop()
        assert cli.connected_addr == ("inproc-qp-fd-b", 7207)
        assert cli.timeouts == 0, "resends expired despite fresh deadlines"
        assert [x.pts for x in out] == [0, 1, 2]

    def test_midstream_failover_resends_inflight(self):
        a = DelayServer("inproc-qp-a", 7203, 0.05).start()
        b = DelayServer("inproc-qp-b", 7204, 0.05).start()
        try:
            p, src, cli, snk = _client(
                "inproc-qp-a", 7203, max_request=8,
                alternate_hosts="inproc-qp-b:7204")
            with p:
                src.push_buffer(Buffer.of(np.zeros((1, 4), np.float32),
                                          pts=0))
                first = snk.pull(timeout=5)  # server A answered request 0
                assert first is not None and first.pts == 0
                # kill the primary with requests already flowing
                a.stop()
                for i in range(1, 6):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            b.stop()
        assert cli.connected_addr == ("inproc-qp-b", 7204)
        assert b.received >= 1  # at least the resent in-flight requests
        # every remaining frame answered exactly once, in order
        assert [x.pts for x in out] == list(range(1, 6))
        for x in out:
            np.testing.assert_array_equal(
                x.tensors[0].np(),
                np.full((1, 4), 2.0 * x.pts, np.float32))

"""ISSUE-9: XLA cost capture, the scrape-time MFU join, hardware-peak
resolution, and per-shard mesh attribution.

Covers the acceptance tests named by the issue:

- the captured static cost EXACTLY equals ``compiled.cost_analysis()``
  for the same executable;
- the ``nns_mfu`` gauge agrees with an InvokeStats-derived hand
  computation on a fake-clock (deterministic device-seconds) run;
- the imbalance gauge is 0.0 on an even split and positive on a forced
  uneven split;
- the unknown-backend fallback exports intensity but no utilization;

plus the join's bucket mapping, pad accounting, the meshscaling
attribution decomposition, and the nns-top MFU / MESH rendering.
"""

import json

import numpy as np
import pytest

from nnstreamer_tpu.filters.api import FilterProps
from nnstreamer_tpu.filters.jax_xla import JaxXlaFilter, register_model
from nnstreamer_tpu.obs import hwspec
from nnstreamer_tpu.obs.meshstat import (MESH_STATS, shard_device_label,
                                         shard_split)
from nnstreamer_tpu.obs.metrics import REGISTRY, observe_invoke_phases
from nnstreamer_tpu.obs.xlacost import XLA_COST, cost_of, flops_bytes


def _fam_samples(snap, name):
    return snap["metrics"].get(name, {}).get("samples", [])


@pytest.fixture(autouse=True)
def _no_hwspec_override():
    prev = hwspec.set_override(None)
    yield
    hwspec.set_override(prev)


# -- capture exactness --------------------------------------------------------


def test_captured_cost_equals_compiled_cost_analysis():
    """The compile-seam capture (from the jit LOWERING) must report the
    same flops / bytes as a full ``compiled.cost_analysis()`` of the
    same computation — the figures are computation-intrinsic."""
    import jax

    w = np.asarray(np.random.RandomState(3).randn(32, 32), np.float32)
    name = register_model("xc_exact", lambda x: x @ w,
                          in_shapes=[(8, 32)], in_dtypes=np.float32)
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model=name))
    row = XLA_COST.get(name, 0)
    assert row is not None and row["flops"] > 0
    compiled = jax.jit(lambda x: (x @ w,)).lower(
        jax.ShapeDtypeStruct((8, 32), np.float32)).compile()
    ca = cost_of(compiled)
    assert row["flops"] == float(ca["flops"])
    assert row["bytes"] == float(ca["bytes accessed"])
    sp.close()


def test_bucket_executable_captured_per_bucket():
    w = np.asarray(np.random.RandomState(4).randn(16, 16), np.float32)
    name = register_model("xc_bucket", lambda x: x @ w,
                          in_shapes=[(16,)], in_dtypes=np.float32)
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model=name))
    frame = [np.zeros((16,), np.float32)]
    sp.invoke_batched([frame] * 4, 4)
    row1 = XLA_COST.get(name, 0)
    row4 = XLA_COST.get(name, 4)
    assert row4 is not None, "bucket-4 executable not captured"
    # the window program carries ~4x the single-frame work
    assert row4["flops"] > 2 * row1["flops"]
    sp.close()


def test_flops_bytes_helper_tolerates_unsupported_stage():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("unsupported")

    assert cost_of(Broken()) == {}
    assert flops_bytes(Broken()) == (0.0, 0.0)


# -- the scrape-time MFU join -------------------------------------------------


def test_mfu_gauge_matches_hand_computation():
    """Fake-clock run: deterministic device seconds fed through the
    SAME histogram the runtime feeds; the exported nns_mfu must equal
    flops x dispatches / (device_seconds x peak) by hand."""
    hwspec.set_override(hwspec.V5E)
    flops = 3.2e9
    XLA_COST.record("xc_handmodel", 0, "cpu", "cpu",
                    {"flops": flops, "bytes accessed": 1.0e6})
    XLA_COST.map_source("xc_handelem", "xc_handmodel")
    # 5 sampled dispatches, 2 ms device each (the fake clock)
    for _ in range(5):
        observe_invoke_phases("element", "xc_handelem", 1,
                              prep_s=1e-4, device_s=2e-3, drain_s=5e-5)
    snap = REGISTRY.snapshot()
    mfu = [s for s in _fam_samples(snap, "nns_mfu")
           if s["labels"].get("source") == "xc_handelem"]
    assert mfu, "nns_mfu sample missing"
    expected = flops * 5 / (5 * 2e-3 * hwspec.V5E.peak_flops)
    assert mfu[0]["value"] == pytest.approx(expected, rel=1e-9)
    bw = [s for s in _fam_samples(snap, "nns_hbm_bw_util")
          if s["labels"].get("source") == "xc_handelem"]
    assert bw[0]["value"] == pytest.approx(
        1.0e6 * 5 / (5 * 2e-3 * hwspec.V5E.hbm_bw), rel=1e-9)
    # the executables table row carries the same live figure plus the
    # roofline classification against the v5e ridge
    row = [r for r in snap["executables"]
           if r["source"] == "xc_handmodel"][0]
    assert row["mfu"] == pytest.approx(expected, rel=1e-9)
    assert row["bound"] == "compute"  # 3200 flops/byte >> v5e ridge


def test_join_windows_deltas_between_scrapes():
    """The second scrape must derive utilization from the NEW samples
    only (delta window), not the cumulative history."""
    hwspec.set_override(hwspec.V5E)
    XLA_COST.record("xc_winmodel", 0, "cpu", "cpu",
                    {"flops": 1e9, "bytes accessed": 1e6})
    XLA_COST.map_source("xc_winelem", "xc_winmodel")
    observe_invoke_phases("element", "xc_winelem", 1, 0.0, 1e-3, 0.0)
    REGISTRY.snapshot()  # primes the window
    observe_invoke_phases("element", "xc_winelem", 1, 0.0, 4e-3, 0.0)
    snap = REGISTRY.snapshot()
    mfu = [s for s in _fam_samples(snap, "nns_mfu")
           if s["labels"].get("source") == "xc_winelem"][0]
    # window = the single 4 ms dispatch, NOT the (1+4)/2 ms cumulative
    assert mfu["value"] == pytest.approx(
        1e9 / (4e-3 * hwspec.V5E.peak_flops), rel=1e-9)


def test_single_frame_hist_bucket_maps_to_bucket0_executable():
    hwspec.set_override(hwspec.V5E)
    XLA_COST.record("xc_b0model", 0, "cpu", "cpu",
                    {"flops": 5e8, "bytes accessed": 5e5})
    XLA_COST.map_source("xc_b0elem", "xc_b0model")
    # the chain path labels its series bucket=1; the executable row is
    # keyed bucket=0 — the join must bridge them
    observe_invoke_phases("element", "xc_b0elem", 1, 0.0, 1e-3, 0.0)
    snap = REGISTRY.snapshot()
    row = [r for r in snap["executables"]
           if r["source"] == "xc_b0model"][0]
    assert row.get("dispatches_window", 0) >= 1
    assert "mfu" in row


def test_unknown_backend_exports_intensity_only():
    """CPU/unknown hardware: flops/bytes/intensity export (they are
    properties of the program) but no utilization gauge is derived."""
    XLA_COST.record("xc_cpumodel", 0, "cpu", "cpu",
                    {"flops": 1e9, "bytes accessed": 1e6})
    XLA_COST.map_source("xc_cpuelem", "xc_cpumodel")
    observe_invoke_phases("element", "xc_cpuelem", 1, 0.0, 1e-3, 0.0)
    snap = REGISTRY.snapshot()
    row = [r for r in snap["executables"]
           if r["source"] == "xc_cpumodel"][0]
    assert row["intensity_flops_per_byte"] == pytest.approx(1e3)
    assert "mfu" not in row and "hbm_bw_util" not in row
    assert "ridge_flops_per_byte" not in row
    assert not any(s["labels"].get("source") == "xc_cpuelem"
                   for s in _fam_samples(snap, "nns_mfu"))
    # the static gauges still export
    assert any(s["labels"].get("source") == "xc_cpumodel"
               for s in _fam_samples(snap, "nns_executable_flops"))


def test_hwspec_resolution():
    assert hwspec.spec_for_platform("tpu") is hwspec.V5E
    assert hwspec.spec_for_platform("cpu") is None
    assert hwspec.spec_for_platform("???") is None
    assert hwspec.V5E.ridge == pytest.approx(197e12 / 819e9)
    prev = hwspec.set_override(hwspec.V5E)
    try:
        assert hwspec.spec_for_platform("cpu") is hwspec.V5E
    finally:
        hwspec.set_override(prev)


# -- mesh attribution ---------------------------------------------------------


def test_shard_split_even_and_uneven():
    assert shard_split(8, 8, 2) == [4, 4]
    assert shard_split(8, 5, 2) == [4, 1]   # pads land on the tail
    assert shard_split(12, 11, 4) == [3, 3, 3, 2]
    assert shard_split(4, 0, 2) == [0, 0]


def test_shard_device_label_respects_data_axis_position():
    """The device list is the mesh array in C order, so a data shard
    is a contiguous slice only when the data axis LEADS; with
    ``mesh=model:2,data:2`` shard 0 is the strided column {dev0, dev2},
    not the flat half [dev0, dev1]."""
    devs = ["D0", "D1", "D2", "D3"]
    trailing = {"axes": [["model", 2], ["data", 2]], "devices": devs,
                "data_axis": "data", "shards": 2}
    assert shard_device_label(trailing, 0) == "D0+1"  # {D0, D2}
    assert shard_device_label(trailing, 1) == "D1+1"  # {D1, D3}
    leading = {"axes": [["data", 2], ["model", 2]], "devices": devs,
               "data_axis": "data", "shards": 2}
    assert shard_device_label(leading, 0) == "D0+1"   # {D0, D1}
    assert shard_device_label(leading, 1) == "D2+1"   # {D2, D3}
    flat = {"axes": [["data", 4]], "devices": devs,
            "data_axis": "data", "shards": 4}
    assert [shard_device_label(flat, i) for i in range(4)] == devs
    no_data = {"axes": [["model", 2]], "devices": devs[:2],
               "data_axis": "data", "shards": 1}
    assert shard_device_label(no_data, 0) == "D0+1"


def test_imbalance_zero_on_even_split_positive_on_uneven():
    """The issue's acceptance pair, through the REAL jax-xla mesh
    window path: full windows split evenly (imbalance 0.0), a forced
    short window pads and skews the split (imbalance > 0)."""
    w = np.asarray(np.random.RandomState(5).randn(16, 16), np.float32)
    name = register_model("xc_meshmodel", lambda x: x @ w,
                          in_shapes=[(16,)], in_dtypes=np.float32)
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model=name,
                             mesh="data:2"))
    frame = [np.zeros((16,), np.float32)]
    sp.invoke_batched([frame] * 4, 4)   # even: 2 + 2
    row = MESH_STATS.get(name)
    assert row["shards"] == 2
    assert row["shard_frames"] == [2, 2]
    assert row["imbalance"] == 0.0
    assert row["pad_slots"] == 0
    snap = REGISTRY.snapshot()
    imb = [s for s in _fam_samples(snap, "nns_shard_imbalance")
           if s["labels"].get("source") == name]
    assert imb and imb[0]["value"] == 0.0
    sp.invoke_batched([frame] * 3, 4)   # forced uneven: 2 + 1, 1 pad
    row = MESH_STATS.get(name)
    assert row["shard_frames"] == [4, 3]
    assert row["imbalance"] > 0.0
    assert row["pad_slots"] == 1
    assert row["dispatches"] == 2
    snap = REGISTRY.snapshot()
    imb = [s for s in _fam_samples(snap, "nns_shard_imbalance")
           if s["labels"].get("source") == name][0]
    assert imb["value"] == pytest.approx(4 / 3.5 - 1.0)
    pads = [s for s in _fam_samples(snap, "nns_mesh_pad_slots_total")
            if s["labels"].get("source") == name][0]
    assert pads["value"] == 1
    sp.close()


def test_indivisible_window_counts_as_replicated():
    w = np.asarray(np.random.RandomState(6).randn(16, 16), np.float32)
    name = register_model("xc_replmodel", lambda x: x @ w,
                          in_shapes=[(16,)], in_dtypes=np.float32)
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model=name,
                             mesh="data:2"))
    frame = [np.zeros((16,), np.float32)]
    sp.invoke_batched([frame] * 3, 3)  # 3 % 2 != 0: no constraint
    row = MESH_STATS.get(name)
    assert row["replicated_dispatches"] == 1
    assert row["imbalance"] == 0.0  # every chip computed everything
    sp.close()


def test_sharded_model_records_mesh_dispatch():
    import jax

    from nnstreamer_tpu.parallel import ShardedModel, make_mesh

    devs = jax.devices("cpu")[:2]
    mesh = make_mesh("data:2", devices=devs)
    m = ShardedModel(mesh, lambda x: x * 2.0, name="xc_shardedfn")
    m(np.zeros((8, 4), np.float32))
    row = MESH_STATS.get("xc_shardedfn")
    assert row is not None
    assert row["shards"] == 2
    assert row["frames"] == 8
    assert row["shard_frames"] == [4, 4]


def test_mesh_attribution_decomposition():
    from nnstreamer_tpu.bench import _mesh_attribution

    base = {"efficiency": 1.0, "host_s_per_dispatch": 0.001,
            "device_s_per_dispatch": 0.009}
    row = {"efficiency": 0.5, "host_s_per_dispatch": 0.004,
           "device_s_per_dispatch": 0.016,
           "shard_frames": [10, 10], "pad_frac": 0.0}
    a = _mesh_attribution(row, base)
    # (h_n - h_1)/(h_n + d_n) and (d_n - d_1)/(h_n + d_n)
    assert a["host_phase"] == pytest.approx(0.003 / 0.020)
    assert a["device_contention"] == pytest.approx(0.007 / 0.020)
    assert a["shard_imbalance"] == 0.0
    assert a["pad_waste"] == 0.0
    assert a["dominant"] == "device_contention"
    assert a["residual"] == pytest.approx(
        0.5 - a["host_phase"] - a["device_contention"], abs=1e-3)


# -- rendering ----------------------------------------------------------------


def test_nns_top_renders_mfu_column_and_mesh_section():
    from nnstreamer_tpu.obs.top import render

    base = {"time": 100.0, "pipelines": [{
        "pipeline": "p", "playing": True, "elements": [{
            "element": "net", "factory": "tensor_filter",
            "stats": {"buffers_in": 10, "buffers_out": 10},
            "filter": {"invokes": 10, "frames": 10, "latency_us": 100,
                       "throughput_milli_fps": 1000,
                       "dispatch_milli_fps": 1000,
                       "avg_batch_occupancy": 1.0,
                       "avg_stream_occupancy": 1.0,
                       "attached_streams": 0, "host_prep_us": 5,
                       "device_us": 90, "host_drain_us": 5,
                       "batch": 1, "model": "m1"}}]}],
        "pools": [], "links": [], "compiles": [], "transfers": [],
        "device_memory": [],
        "executables": [{"source": "m1", "bucket": 0,
                         "placement": "mesh(data:2)", "platform": "tpu",
                         "flops": 1e9, "bytes": 1e6,
                         "peak_memory_bytes": 1024,
                         "peak_memory_estimated": True, "compiles": 1,
                         "intensity_flops_per_byte": 1000.0,
                         "mfu": 0.4321}],
        "mesh": [{"source": "m1", "axes": [["data", 2]],
                  "devices": ["TPU:0", "TPU:1"], "data_axis": "data",
                  "shards": 2, "dispatches": 10, "frames": 100,
                  "slots": 104, "pad_slots": 4,
                  "pad_frac": 4 / 104.0, "replicated_dispatches": 0,
                  "shard_frames": [52, 48],
                  "imbalance": 52 / 50.0 - 1.0}]}
    cur = json.loads(json.dumps(base))
    cur["time"] = 101.0
    out = render(cur, base)
    assert "MFU%" in out
    assert "43.21" in out            # the element row's MFU column
    assert "MESH" in out and "TPU:1" in out
    assert "data:2" in out
    # both shard rows render with their frame counts
    assert "52" in out and "48" in out


def test_snapshot_executables_and_mesh_are_lists():
    snap = REGISTRY.snapshot()
    assert isinstance(snap["executables"], list)
    assert isinstance(snap["mesh"], list)

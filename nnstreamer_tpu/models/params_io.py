"""Checkpoint interop: npz / safetensors ⇄ zoo parameter pytrees.

Parity target: the reference consumes framework-native checkpoint
files (.tflite weights, caffemodel, .pb — e.g.
tensor_filter_tensorflow_lite.cc:242-280); this module is the
framework's own interchange layer so pretrained weights move in and
out of the zoo without pickle:

- ``.npz``: numpy archive with ``/``-joined pytree paths as keys.
- ``.safetensors``: hand-rolled reader/writer for the de-facto
  HuggingFace weight format (8-byte LE header length + JSON header +
  raw little-endian tensor bytes) — no third-party dependency, same
  policy as the wire codecs.

Both formats carry the model-file metadata the jax-xla filter needs
(``apply`` import path, input shapes/dtypes), so a weights file is
loadable directly via ``tensor_filter model=weights.safetensors``.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# -- pytree ⇄ flat dict -------------------------------------------------------


def _escape_seg(key: str, sep: str) -> str:
    """Escape a dict key so it survives as one path segment even when it
    contains the separator (GraphDef node names routinely carry "/")."""
    return key.replace("\\", "\\\\").replace(sep, "\\" + sep)


def _split_path(path: str, sep: str) -> List[str]:
    """Split on unescaped separators and unescape each segment —
    inverse of :func:`_escape_seg` applied per segment."""
    parts: List[str] = []
    cur: List[str] = []
    i, n, w = 0, len(path), len(sep)
    while i < n:
        c = path[i]
        if c == "\\" and i + 1 < n:
            cur.append(path[i + 1])
            i += 2
            continue
        if path.startswith(sep, i):
            parts.append("".join(cur))
            cur = []
            i += w
            continue
        cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


def flatten_params(params: Any, sep: str = "/") -> Dict[str, np.ndarray]:
    """Flatten a nested dict/list/tuple pytree of arrays into
    {"path/to/leaf": ndarray}.  List/tuple indices become ``#i``
    segments — the marker keeps them distinguishable from dicts whose
    keys happen to be digit strings (e.g. torch-style ``{"0": ...}``),
    so the round trip is structure-exact.  Dict keys containing the
    separator (e.g. TF node names like "MobilenetV1/Conv2d_0/weights")
    are backslash-escaped so they stay ONE segment instead of silently
    splitting into a different nested structure.  Non-array leaves
    (e.g. ``num_classes`` ints) are stored as 0-d arrays and restored
    as python scalars."""
    out: Dict[str, np.ndarray] = {}

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                seg = _escape_seg(str(k), sep)
                walk(f"{prefix}{sep}{seg}" if prefix else seg, v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                seg = f"#{i}"
                walk(f"{prefix}{sep}{seg}" if prefix else seg, v)
        else:
            out[prefix] = np.asarray(node)

    walk("", params)
    return out


def unflatten_params(flat: Dict[str, np.ndarray], sep: str = "/",
                     escaped: bool = True) -> Any:
    """Inverse of :func:`flatten_params`: ``#i`` segments rebuild
    lists; plain digit keys stay dict keys; backslash-escaped
    separators stay inside their segment; 0-d arrays of int/float
    come back as python scalars (zoo params like ``num_classes``).

    ``escaped=False`` reproduces the v2 on-disk layout (plain split,
    backslashes literal) for files written before the escape scheme —
    loaders select it from the file's format marker."""
    root: Dict = {}
    for path, leaf in flat.items():
        parts = _split_path(path, sep) if escaped else path.split(sep)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        if leaf.ndim == 0:
            v = leaf.item()
        else:
            v = leaf
        node[parts[-1]] = v

    def fix(node):
        if not isinstance(node, dict):
            return node
        keys = list(node)
        if keys and all(k.startswith("#") and k[1:].isdigit()
                        for k in keys):
            return [fix(node[k]) for k in sorted(keys,
                                                 key=lambda k: int(k[1:]))]
        return {k: fix(v) for k, v in node.items()}

    return fix(root)


# -- npz ----------------------------------------------------------------------

_META_KEY = "__nns_meta__"


def save_npz(path: str, params: Any, apply: Optional[str] = None,
             in_shapes: Optional[Sequence] = None,
             in_dtypes: Any = None) -> str:
    """Write a pytree as .npz; ``apply`` ("module:callable") and input
    schema ride along so the file works as a tensor_filter model."""
    flat = flatten_params(params)
    meta = {"apply": apply, "in_shapes": in_shapes,
            "in_dtypes": np.dtype(in_dtypes).name
            if in_dtypes is not None else None,
            # structure format marker: v3 = backslash-escaped
            # separators inside dict-key segments; v2 = plain split
            # ("#i" list-index segments in both)
            "format": "nns-params-v3"}
    flat[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), np.uint8)
    np.savez(path, **flat)
    return path


def load_npz(path: str) -> Tuple[Any, Dict[str, Any]]:
    """Returns (params pytree, metadata dict)."""
    with np.load(path, allow_pickle=False) as z:
        flat = {k: z[k] for k in z.files}
    meta: Dict[str, Any] = {}
    blob = flat.pop(_META_KEY, None)
    if blob is not None:
        meta = json.loads(bytes(blob.tobytes()).decode("utf-8"))
    return unflatten_params(
        flat, escaped=meta.get("format") == "nns-params-v3"), meta


# -- safetensors --------------------------------------------------------------

_ST_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U64": np.uint64, "U32": np.uint32, "U16": np.uint16, "U8": np.uint8,
    "BOOL": np.bool_,
}


def _st_name(dt: np.dtype) -> str:
    if dt.name == "bfloat16":
        return "BF16"
    for name, np_t in _ST_DTYPES.items():
        if np.dtype(np_t) == dt:
            return name
    raise ValueError(f"safetensors: unsupported dtype {dt}")


def _st_np(name: str):
    if name == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_ST_DTYPES[name])
    except KeyError:
        raise ValueError(f"safetensors: unsupported dtype {name!r}") \
            from None


def save_safetensors(path: str, params: Any,
                     metadata: Optional[Dict[str, str]] = None) -> str:
    """Write a pytree in safetensors layout (sorted keys, little-endian
    raw bytes, ``__metadata__`` for the apply/schema strings)."""
    flat = flatten_params(params)
    header: Dict[str, Any] = {}
    md = {str(k): str(v) for k, v in (metadata or {}).items()}
    md.setdefault("format", "nns-params-v3")  # escaped-sep segments
    header["__metadata__"] = md
    off = 0
    chunks: List[bytes] = []
    for name in sorted(flat):
        arr = np.ascontiguousarray(flat[name])
        raw = arr.tobytes()
        header[name] = {"dtype": _st_name(arr.dtype),
                        "shape": list(arr.shape),
                        "data_offsets": [off, off + len(raw)]}
        chunks.append(raw)
        off += len(raw)
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hdr)))
        f.write(hdr)
        for c in chunks:
            f.write(c)
    return path


def load_safetensors(path: str) -> Tuple[Any, Dict[str, str]]:
    """Returns (params pytree, metadata dict).  Validates offsets
    against the file size before touching tensor bytes."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        if hlen > size - 8:
            raise ValueError(f"safetensors: header length {hlen} exceeds "
                             f"file size {size}")
        header = json.loads(f.read(hlen).decode("utf-8"))
        base = 8 + hlen
        meta = header.pop("__metadata__", {}) or {}
        flat: Dict[str, np.ndarray] = {}
        for name, desc in header.items():
            dt = _st_np(desc["dtype"])
            lo, hi = desc["data_offsets"]
            nbytes = int(np.prod(desc["shape"], dtype=np.int64)) * \
                dt.itemsize if desc["shape"] else dt.itemsize
            if lo < 0 or hi < lo or hi - lo != nbytes or \
                    base + hi > size:
                raise ValueError(
                    f"safetensors: bad offsets for {name!r}")
            f.seek(base + lo)
            flat[name] = np.frombuffer(
                f.read(hi - lo), dt).reshape(desc["shape"]).copy()
    # only v3 files escape separators; v2 files and safetensors from
    # external tools (whose names may carry literal backslashes) use the
    # plain split
    return unflatten_params(
        flat, escaped=meta.get("format") == "nns-params-v3"), dict(meta)


# -- low-precision residency ---------------------------------------------------


def weights_to_bf16(params: Any) -> Any:
    """Return a copy of a params pytree with float32 WEIGHT leaves
    (ndim >= 2: conv kernels, dense matrices, embeddings) cast to
    bfloat16 so they are bf16-RESIDENT in HBM — half the weight-read
    traffic of f32, and the compute path already consumes bf16 (the
    zoo's apply fns cast with ``.astype(dtype)``, a no-op on bf16
    arrays).  1-D leaves (biases, batch-norm stats) stay float32:
    they are tiny and precision-sensitive."""
    import jax
    import jax.numpy as jnp

    def cast(leaf):
        a = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        if getattr(a, "dtype", None) == np.float32 and \
                getattr(a, "ndim", 0) >= 2:
            return jnp.asarray(a, jnp.bfloat16) if hasattr(
                leaf, "devices") else np.asarray(
                a, dtype=jnp.bfloat16.dtype)
        return leaf

    return jax.tree_util.tree_map(cast, params)

"""YOLO family: v8 wire-layout parity with the decoder, and the
end-to-end on-device head through real pipelines.

Parity: the reference's yolo decoder strategies (box_properties/yolo.cc
v5/v8 layouts); the family itself is TPU-native (models/yolo.py)."""

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.models.yolo import (
    register_yolo,
    yolo_detect_apply,
    yolo_init,
    yolo_raw_apply,
)
from nnstreamer_tpu.runtime import parse_launch

SIZE, NCLS = 64, 5


@pytest.fixture(scope="module")
def params():
    import jax

    return yolo_init(jax.random.PRNGKey(0), num_classes=NCLS, width=8)


def _frame(seed=0, batch=1):
    return np.random.default_rng(seed).uniform(
        0, 1, (batch, SIZE, SIZE, 3)).astype(np.float32)


class TestRawLayout:
    def test_v8_wire_shape_and_ranges(self, params):
        out = np.asarray(yolo_raw_apply(params, _frame()))
        # (B, 4+C, A) with A = sum of the stride-8/16/32 grids
        a = sum((SIZE // s) ** 2 for s in (8, 16, 32))
        assert out.shape == (1, 4 + NCLS, a)
        xywh, cls = out[0, :4], out[0, 4:]
        assert (cls >= 0).all() and (cls <= 1).all()
        assert (xywh[0] >= 0).all() and (xywh[0] <= SIZE).all()  # cx px
        assert (xywh[2] > 0).all()                               # w px

    def test_host_yolov8_decoder_consumes_it(self, params):
        """The raw layout must flow through tensor_decoder's yolov8
        scheme exactly as a real v8 model's output would."""
        out = np.asarray(yolo_raw_apply(params, _frame()))
        a = out.shape[-1]
        p = parse_launch(
            "appsrc name=src ! tensor_decoder mode=bounding_boxes "
            f"option1=yolov8 option3=0.05:0.5 option4={SIZE}:{SIZE} "
            f"option5={SIZE}:{SIZE} ! tensor_sink name=out")
        p["src"].spec = TensorsSpec.parse(
            f"{a}:{4 + NCLS}:1", "float32")
        got = []
        p["out"].connect(lambda b: got.append(b))
        with p:
            p["src"].push_buffer(Buffer.of(out))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=60)
        assert len(got) == 1
        frame = got[0].tensors[0].np()
        assert frame.shape == (SIZE, SIZE, 4)
        dets = got[0].meta["detections"]
        for d in dets:
            assert 0 <= d.class_id < NCLS and d.score >= 0.05


class TestEndToEnd:
    def test_device_head_postprocess_contract(self, params):
        b, c, s, n = yolo_detect_apply(params, _frame(batch=2),
                                       max_out=10)
        assert np.asarray(b).shape == (2, 10, 4)
        assert np.asarray(c).shape == (2, 10)
        assert np.asarray(s).shape == (2, 10)
        assert np.asarray(n).shape == (2,)
        bb = np.asarray(b)
        assert (bb[..., 2] >= bb[..., 0] - 1e-6).all()  # ymax >= ymin
        # scores sorted descending per frame (top-k contract)
        ss = np.asarray(s)
        assert (np.diff(ss, axis=-1) <= 1e-6).all()

    def test_full_pipeline_with_device_overlay(self):
        """device head → bounding_boxes option7=device: detection AND
        overlay never leave the accelerator (same composition as the
        SSD composite bench)."""
        from nnstreamer_tpu.filters.jax_xla import unregister_model

        name = register_yolo("test_yolo_e2e", batch=2, image_size=SIZE,
                             num_classes=NCLS, max_out=8, seed=0)
        try:
            p = parse_launch(
                "appsrc name=src ! "
                f"tensor_filter framework=jax-xla model={name} ! "
                "tensor_decoder mode=bounding_boxes "
                "option1=mobilenet-ssd-postprocess "
                f"option4={SIZE}:{SIZE} option7=device ! "
                "tensor_sink name=out")
            p["src"].spec = TensorsSpec.from_shapes(
                [(2, SIZE, SIZE, 3)], np.float32)
            got = []
            p["out"].connect(lambda b: got.append(b))
            with p:
                p["src"].push_buffer(Buffer.of(_frame(batch=2)))
                p["src"].end_of_stream()
                assert p.wait_eos(timeout=120)
            assert got[0].tensors[0].np().shape == (2, SIZE, SIZE, 4)
            assert "detections_device" in got[0].meta
        finally:
            unregister_model(name)

"""Checkpoint backends for in-pipeline training.

Parity target: ``model-save-path`` / ``model-load-path`` on the
reference trainer (gsttensor_trainer.c:96-98).  Two formats:

- file paths (``.pkl``/``.msgpack``) save the jax-xla filter's loadable
  model format (``filters/jax_xla.save_params_model``) — inference
  pipelines hot-load the trained model directly;
- directory paths save **orbax** checkpoints — the TPU-idiomatic
  format: async-safe, multi-host aware (each host writes its shard),
  and restorable onto a different mesh.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def is_orbax_path(path: str) -> bool:
    """Directory-shaped paths (trailing separator or an extension-less
    basename) use orbax; ANY file extension means a single-file format
    (`.pkl`/`.msgpack` loadable models; unknown extensions still go to
    the file path so `model.ckpt` is never silently turned into an
    orbax directory)."""
    if path.endswith(os.sep) or path.endswith("/"):
        return True
    return os.path.splitext(os.path.basename(path))[1] == ""


def save_orbax(path: str, pytree: Any) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, pytree, force=True)
    ckptr.wait_until_finished()


def load_orbax(path: str, template: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        import jax

        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") else x, template)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)

"""pytorch filter framework: TorchScript models as pipeline filters.

Parity target: /root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_pytorch.cc (TorchScript through libtorch).  The adapter
runs models through torch on the host CPU — interop/migration path;
the XLA importers are the TPU performance path.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.filter import FilterSingle
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.runtime import parse_launch

torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def scripted_mlp(tmp_path_factory):
    torch.manual_seed(0)
    m = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4))
    path = tmp_path_factory.mktemp("pt") / "mlp.pt"
    torch.jit.script(m).save(str(path))
    return str(path), m


class TestSingleShot:
    def test_invoke_matches_eager(self, scripted_mlp):
        path, m = scripted_mlp
        fs = FilterSingle(framework="pytorch", model=path,
                          input_spec=TensorsSpec.parse("8:2", "float32"))
        assert fs.out_spec.tensors[0].dims == (4, 2)
        x = np.random.default_rng(1).standard_normal((2, 8)).astype(
            np.float32)
        out = fs.invoke([x])[0]
        with torch.no_grad():
            want = m(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5,
                                   atol=1e-6)

    def test_reshape_reinfers_output(self, scripted_mlp):
        path, _ = scripted_mlp
        fs = FilterSingle(framework="pytorch", model=path,
                          input_spec=TensorsSpec.parse("8:2", "float32"))
        fs.set_input_info(TensorsSpec.parse("8:5", "float32"))
        out = fs.invoke([np.zeros((5, 8), np.float32)])[0]
        assert np.asarray(out).shape == (5, 4)

    def test_incompatible_reshape_raises_filter_error(self, scripted_mlp):
        """A rejected reshape surfaces as FilterError (NegotiationError
        at the element layer) and leaves the old in/out specs intact
        (review finding)."""
        path, _ = scripted_mlp
        fs = FilterSingle(framework="pytorch", model=path,
                          input_spec=TensorsSpec.parse("8:2", "float32"))
        with pytest.raises(FilterError, match="rejects input"):
            fs.set_input_info(TensorsSpec.parse("7:2", "float32"))
        assert fs.subplugin._in_spec.tensors[0].dims == (8, 2)
        assert fs.subplugin._out_spec.tensors[0].dims == (4, 2)

    def test_missing_input_spec_rejected(self, scripted_mlp):
        path, _ = scripted_mlp
        with pytest.raises(FilterError, match="input spec"):
            FilterSingle(framework="pytorch", model=path)

    def test_bad_file_rejected(self, tmp_path):
        bad = tmp_path / "junk.pt"
        bad.write_bytes(b"\x00" * 32)
        with pytest.raises(FilterError):
            FilterSingle(framework="pytorch", model=str(bad),
                         input_spec=TensorsSpec.parse("8:2", "float32"))


class TestPipeline:
    def test_auto_detected_from_extension(self, scripted_mlp):
        path, m = scripted_mlp
        p = parse_launch(
            f"appsrc name=src ! tensor_filter model={path} "
            "input=8:2 inputtype=float32 ! appsink name=out")
        p["src"].spec = TensorsSpec.parse("8:2", "float32", rate=0)
        x = np.random.default_rng(2).standard_normal((2, 8)).astype(
            np.float32)
        with p:
            p["src"].push_buffer(Buffer.of(x))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=60)
            out = p["out"].pull(timeout=2)
        with torch.no_grad():
            want = m(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(out[0].np(), want, rtol=1e-5,
                                   atol=1e-6)

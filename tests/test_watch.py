"""`obs/watch.py` — alerting watchdog tests (ISSUE-10 surface).

Rule grammar (TOML/JSON, symbolic values, malformed files), the shared
histogram-quantile helper, the bounded series store (rate / level /
windowed quantiles, rate-from-zero for series born mid-run, counter
resets), all three rule kinds (threshold incl. ratio + `for`, dual-
window SLO burn in histogram and counter-ratio mode, robust-z drift
anomaly incl. the bounded baseline window), alert actions (registry
export, flight-recorder trigger exactly once per episode, pipeline-bus
WARNING), the strict kill-switch no-op, fleet mode over the shared
scrape client (endpoint-down), the nns-top ALERTS section, `/healthz`
alerts, the `nns-watch` CLI, and the registry-scrape-vs-`Pipeline.stop`
race (satellite)."""

import io
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core import TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.obs.metrics import (MetricsRegistry, REGISTRY,
                                        bucket_quantile)
from nnstreamer_tpu.obs import watch as watch_mod
from nnstreamer_tpu.obs.watch import (AlertRule, RuleError, SeriesStore,
                                      Watch, default_rules, lint_rule,
                                      load_rules, parse_rules)
from nnstreamer_tpu.runtime import Pipeline

SHAPE = (4,)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_watch", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_watch")


def _gauge_snap(name, value, labels=None, pools=None):
    return {"pools": pools or [],
            "metrics": {name: {"name": name, "kind": "gauge",
                               "help": "",
                               "samples": [{"labels": labels or {},
                                            "value": value}]}}}


def _counter_snap(name, value, labels=None):
    snap = _gauge_snap(name, value, labels)
    snap["metrics"][name]["kind"] = "counter"
    return snap


def _src(snap_fn):
    return lambda: [{"endpoint": "local", "snap": snap_fn(),
                     "error": None}]


# -- shared histogram-quantile helper (satellite: one definition) ------------


def test_bucket_quantile_interpolates():
    bounds = (1.0, 2.0, 4.0, float("inf"))
    # 10 obs <=1, 10 in (1,2], none above 2
    assert bucket_quantile(bounds, [10, 10, 0, 0], 0.5) == 1.0
    # p75 lands mid-bucket: 5 of 10 into (1,2]
    assert bucket_quantile(bounds, [10, 10, 0, 0], 0.75) == 1.5
    assert bucket_quantile(bounds, [0, 0, 0, 0], 0.99) is None
    # quantile in the +Inf bucket: nothing to interpolate toward
    assert bucket_quantile(bounds, [0, 0, 0, 5], 0.99) is None
    # first-bucket interpolation anchors at 0
    assert bucket_quantile(bounds, [10, 0, 0, 0], 0.5) == 0.5


def test_admission_p99_uses_shared_quantile(monkeypatch):
    """The admission controller's histogram-derived p99 routes through
    the one shared bucket_quantile definition."""
    from nnstreamer_tpu.runtime.admission import AdmissionController

    reg = MetricsRegistry()
    hist = reg.histogram("t_adm", buckets=(0.01, 0.02, 0.04)) \
        .labels()
    ctl = AdmissionController(slo_s=0.05, hist=hist)
    for _ in range(ctl.RECOMPUTE_EVERY * 4):
        ctl.observe(0.015)
    p99 = ctl.p99_s
    assert 0.01 < p99 <= 0.02, p99
    buckets, _s, _n = hist.hist_state()
    assert p99 == pytest.approx(
        bucket_quantile(hist.bucket_bounds, buckets, 0.99))


# -- rule grammar -------------------------------------------------------------


def test_parse_rules_json_and_symbolic(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"rule": [
        {"name": "brk", "kind": "threshold",
         "metric": "nns_edge_breaker_state", "op": ">=",
         "value": "open", "for": "10s", "severity": "critical"},
        {"name": "burn", "kind": "slo_burn",
         "metric": "nns_admission_latency_seconds",
         "fast": "500ms", "slow": "2m"},
    ]}))
    rules = load_rules(str(path))
    assert rules[0].value == 2.0 and rules[0].for_s == 10.0
    assert rules[1].fast_s == 0.5 and rules[1].slow_s == 120.0


def test_parse_rules_toml(tmp_path):
    pytest.importorskip("tomllib")
    path = tmp_path / "rules.toml"
    path.write_text(
        '[[rule]]\nname = "brk"\nkind = "threshold"\n'
        'metric = "nns_edge_breaker_state"\nop = ">="\n'
        'value = "open"\nfor = "10s"\n')
    rules = load_rules(str(path))
    assert rules[0].name == "brk" and rules[0].value == 2.0


@pytest.mark.parametrize("doc,msg", [
    ({"rule": [{"name": "r", "kind": "nope", "metric": "nns_mfu"}]},
     "unknown kind"),
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu",
                "frobnicate": 1}]}, "unknown key"),
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu",
                "op": "~"}]}, "unknown op"),
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu",
                "value": "wide-open"}]}, "symbolic"),
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu",
                "for": "10parsecs"}]}, "duration"),
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu"},
               {"name": "r", "kind": "threshold",
                "metric": "nns_mfu"}]}, "duplicate"),
    ({"rule": [{"kind": "threshold", "metric": "nns_mfu"}]}, "name"),
    ({"rule": []}, "no rules"),
    ({}, "no top-level"),
], ids=["kind", "key", "op", "symbol", "duration", "dupe", "noname",
        "empty", "shapeless"])
def test_malformed_rules_raise(doc, msg):
    with pytest.raises(RuleError, match=msg):
        parse_rules(doc)


def test_lint_rule_catalog_checks():
    bad_family = AlertRule(name="r", kind="threshold",
                           metric="nns_never_exported_total")
    assert any("ever exports" in p for p in lint_rule(bad_family))
    bad_signal = AlertRule(name="r", kind="threshold",
                           metric="nns_mfu", signal="rate")
    assert any("does not exist" in p for p in lint_rule(bad_signal))
    burn_gauge = AlertRule(name="r", kind="slo_burn", metric="nns_mfu")
    assert any("gauge" in p for p in lint_rule(burn_gauge))
    burn_counter_noper = AlertRule(
        name="r", kind="slo_burn", metric="nns_admission_shed_total")
    assert any("needs per=" in p for p in lint_rule(burn_counter_noper))
    # unsatisfiable lower-side drift: |z| <= 1/rel_floor on a collapse
    unsat = AlertRule(name="r", kind="anomaly", metric="nns_mfu",
                      z=8.0, side="lower", rel_floor=0.25)
    assert any("never fire" in p for p in lint_rule(unsat))


def test_default_pack_lints_clean():
    rules = default_rules()
    assert len(rules) >= 10
    for r in rules:
        assert lint_rule(r) == [], (r.name, lint_rule(r))


# -- series store -------------------------------------------------------------


def test_store_counter_rate_and_reset():
    store = SeriesStore()
    for ts, v in ((1.0, 100.0), (2.0, 110.0), (3.0, 5.0), (4.0, 10.0)):
        store.ingest("local",
                     _counter_snap("nns_edge_timeouts_total", v), ts)
    (_key, s), = store.match("nns_edge_timeouts_total", {})
    rates = [v for _t, v in s.rings["rate"]]
    # first tick = baseline, 100->110 = 10/s, reset skipped, 5->10 = 5/s
    assert rates == [10.0, 5.0]


def test_store_rate_from_zero_for_new_series():
    """A counter born AFTER the store's first tick carries its whole
    value as this window's increments (first error must alarm)."""
    store = SeriesStore()
    empty = {"metrics": {}}
    store.ingest("local", empty, 1.0)
    store.ingest("local",
                 _counter_snap("nns_element_errors_total", 2.0), 2.0)
    (_k, s), = store.match("nns_element_errors_total", {})
    assert [v for _t, v in s.rings["rate"]] == [2.0]
    # but on the store's FIRST tick, history is not news
    store2 = SeriesStore()
    store2.ingest("local",
                  _counter_snap("nns_element_errors_total", 99.0), 1.0)
    (_k, s2), = store2.match("nns_element_errors_total", {})
    assert list(s2.rings["rate"]) == []


def test_store_histogram_windowed_quantiles():
    store = SeriesStore()

    def snap(cums):
        samples = []
        for le, c in zip(("0.001", "0.01", "0.1", "+Inf"), cums):
            samples.append({"labels": {"pool": "p", "le": le},
                            "value": c,
                            "name": "nns_admission_latency_seconds_bucket"})
        return {"metrics": {"nns_admission_latency_seconds": {
            "name": "nns_admission_latency_seconds",
            "kind": "histogram", "help": "", "samples": samples}}}

    store.ingest("local", snap([0, 0, 0, 0]), 1.0)
    store.ingest("local", snap([100, 100, 100, 100]), 2.0)
    (_k, s), = store.match("nns_admission_latency_seconds", {})
    # all 100 obs <= 1ms: p99 interpolates inside the first bucket
    p99 = s.last("p99")[1]
    assert 0 < p99 <= 0.001
    # now 100 more, all in (10ms, 100ms]
    store.ingest("local", snap([100, 100, 200, 200]), 3.0)
    assert 0.01 < s.last("p99")[1] <= 0.1


def test_store_bounded_rings_and_series_cap():
    store = SeriesStore(ring_points=8, max_series=2)
    for i in range(20):
        snap = {"metrics": {"nns_mfu": {
            "name": "nns_mfu", "kind": "gauge", "help": "",
            "samples": [{"labels": {"source": str(i % 4)},
                         "value": 1.0}]}}}
        store.ingest("local", snap, float(i))
    assert len(store) == 2
    assert store.dropped_series > 0
    for _k, s in store.match("nns_mfu", {}):
        assert len(s.rings["level"]) <= 8


# -- threshold rules ----------------------------------------------------------


def test_threshold_for_duration_and_resolve():
    state = {"v": 0.0}
    w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                               metric="nns_edge_breaker_state",
                               op=">=", value="open", for_s=2.0,
                               severity="critical")],
              registry=MetricsRegistry(),
              source=_src(lambda: _gauge_snap(
                  "nns_edge_breaker_state", state["v"],
                  {"link": "l", "peer": "p", "kind": "edge"})))
    assert w.sample_once(1.0) == []
    state["v"] = 2.0
    assert w.sample_once(2.0) == []      # bad, but not for 2s yet
    assert w.sample_once(3.0) == []
    fired = w.sample_once(4.0)           # held 2s: fires
    assert [e["rule"] for e in fired] == ["brk"]
    detail = fired[0]["detail"]
    assert detail["series"] == {"link": "l", "peer": "p",
                                "kind": "edge"}
    assert detail["points"], "offending series snapshot missing"
    state["v"] = 0.0
    assert w.sample_once(5.0) == []
    alerts = {a["rule"]: a for a in w.alerts()}
    assert not alerts["brk"]["firing"] and alerts["brk"]["fired"] == 1


def test_threshold_ratio_queue_saturation():
    def snap(depth):
        return {"metrics": {
            "nns_queue_depth": {
                "name": "nns_queue_depth", "kind": "gauge", "help": "",
                "samples": [{"labels": {"pipeline": "p",
                                        "element": "q"},
                             "value": depth}]},
            "nns_queue_capacity": {
                "name": "nns_queue_capacity", "kind": "gauge",
                "help": "",
                "samples": [{"labels": {"pipeline": "p",
                                        "element": "q"},
                             "value": 10.0}]},
        }}

    state = {"d": 1.0}
    w = Watch(rules=[AlertRule(name="qsat", kind="threshold",
                               metric="nns_queue_depth",
                               per="nns_queue_capacity",
                               op=">=", value=0.9)],
              registry=MetricsRegistry(),
              source=_src(lambda: snap(state["d"])))
    assert w.sample_once(1.0) == []
    state["d"] = 9.0
    assert [e["rule"] for e in w.sample_once(2.0)] == ["qsat"]


# -- anomaly rules ------------------------------------------------------------


def test_anomaly_upper_fires_on_spike_only():
    vals = [100.0, 101.0, 99.0, 100.0, 102.0, 98.0, 100.0, 101.0,
            99.0, 100.0]
    state = {"v": 0.0}
    w = Watch(rules=[AlertRule(name="drift", kind="anomaly",
                               metric="nns_pool_latency_us", z=8.0,
                               side="upper", min_samples=8,
                               rel_floor=0.35)],
              registry=MetricsRegistry(),
              source=_src(lambda: _gauge_snap("nns_pool_latency_us",
                                              state["v"],
                                              {"pool": "x"})))
    now = 0.0
    for v in vals:
        state["v"] = v
        now += 1.0
        assert w.sample_once(now) == [], f"false positive at {v}"
    state["v"] = 104.0  # noise, under the floor
    now += 1.0
    assert w.sample_once(now) == []
    state["v"] = 800.0  # 8x the baseline: decisively out of regime
    now += 1.0
    assert [e["rule"] for e in w.sample_once(now)] == ["drift"]
    assert w.alert_log[-1]["detail"]["zscore"] >= 8.0


def test_anomaly_lower_side_mfu_collapse():
    # NOTE the z/rel_floor pairing: on a lower-side rule the drop is
    # bounded by the series itself (a collapse to 0 is -median), so
    # z*rel_floor must stay < 1 for the rule to be satisfiable — the
    # default pack's mfu-collapse uses the same 3.5 x 0.25
    state = {"v": 0.4}
    w = Watch(rules=[AlertRule(name="mfu", kind="anomaly",
                               metric="nns_mfu", z=3.5, side="lower",
                               min_samples=8, rel_floor=0.25)],
              registry=MetricsRegistry(),
              source=_src(lambda: _gauge_snap("nns_mfu", state["v"],
                                              {"source": "m",
                                               "bucket": "8",
                                               "placement": "tpu"})))
    now = 0.0
    for _ in range(10):
        now += 1.0
        assert w.sample_once(now) == []
    state["v"] = 0.01  # collapse
    now += 1.0
    assert [e["rule"] for e in w.sample_once(now)] == ["mfu"]


def test_anomaly_baseline_window_ages_out_old_regime():
    """Startup values 40x the steady state must age OUT of the
    baseline (bounded baseline_points), not poison the MAD forever."""
    state = {"v": 40000.0}
    w = Watch(rules=[AlertRule(name="drift", kind="anomaly",
                               metric="nns_pool_latency_us", z=8.0,
                               side="upper", min_samples=8,
                               rel_floor=0.35, baseline_points=16)],
              registry=MetricsRegistry(),
              source=_src(lambda: _gauge_snap("nns_pool_latency_us",
                                              state["v"],
                                              {"pool": "x"})))
    now = 0.0
    for _ in range(6):  # compile-decay regime
        now += 1.0
        w.sample_once(now)
        state["v"] *= 0.5
    state["v"] = 300.0  # steady state, 20+ ticks: old regime ages out
    for _ in range(20):
        now += 1.0
        w.sample_once(now)
    state["v"] = 3000.0  # 10x steady: must fire despite the old spikes
    now += 1.0
    assert [e["rule"] for e in w.sample_once(now)] == ["drift"]


def test_stale_series_resolves_alert_and_evicts():
    """A series that stops appearing in snapshots (its pipeline/link
    died) must stop satisfying rules — the alert resolves instead of
    firing forever on the frozen last point — and eventually evicts."""
    present = {"on": True}

    def snap():
        if not present["on"]:
            return {"metrics": {}}
        return _gauge_snap("nns_edge_breaker_state", 2.0,
                           {"link": "l", "peer": "p", "kind": "edge"})

    w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                               metric="nns_edge_breaker_state",
                               op=">=", value="open")],
              registry=MetricsRegistry(), source=_src(snap))
    now = 0.0
    now += 1.0
    assert [e["rule"] for e in w.sample_once(now)] == ["brk"]
    present["on"] = False  # the link's source object is gone
    for _ in range(SeriesStore.STALE_TICKS + 1):
        now += 1.0
        w.sample_once(now)
    alerts = {a["rule"]: a for a in w.alerts()}
    assert not alerts["brk"]["firing"], "stale series kept alert firing"
    for _ in range(SeriesStore.EVICT_TICKS + 1):
        now += 1.0
        w.sample_once(now)
    assert w.store.match("nns_edge_breaker_state", {}) == []
    assert len(w.store) == 0, "ghost series not evicted"


def test_bus_warning_rate_limited_across_episodes(monkeypatch):
    """An oscillating rule fires a new episode per tick; the bus
    WARNING action is limited to one per second while the counter
    still records every episode."""
    from nnstreamer_tpu.runtime.events import MessageKind

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="watch-ratelimit")
    src = AppSrc(name="src", spec=spec, max_buffers=8)
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, sink).link(src, sink)
    warnings = []
    p.bus.add_watch(lambda m: warnings.append(m)
                    if m.kind == MessageKind.WARNING else None)
    p.start()
    try:
        state = {"v": 0.0}
        w = Watch(rules=[AlertRule(name="osc", kind="threshold",
                                   metric="nns_edge_breaker_state",
                                   op=">=", value="open")],
                  registry=REGISTRY,
                  source=_src(lambda: _gauge_snap(
                      "nns_edge_breaker_state", state["v"],
                      {"link": "l", "peer": "rl", "kind": "edge"})))
        now = 0.0
        for i in range(10):  # 5 fire/resolve episodes, back to back
            state["v"] = 2.0 if i % 2 == 0 else 0.0
            now += 0.05
            w.sample_once(now)
        st = w._states["osc"]
        assert st.fired == 5
        assert len(warnings) == 1, [m.data for m in warnings]
    finally:
        src.end_of_stream()
        p.stop()


def test_endpoint_down_rule_name_is_reserved():
    with pytest.raises(RuleError, match="reserved"):
        Watch(rules=[AlertRule(name="endpoint-down", kind="threshold",
                               metric="nns_mfu")])
    assert any("reserved" in p for p in lint_rule(
        AlertRule(name="endpoint-down", kind="threshold",
                  metric="nns_mfu")))


def test_histogram_bucket_layout_change_resyncs_clean():
    """A family whose bucket layout changes mid-run (process restart
    behind the same endpoint) must drop its old-length delta rows —
    no truncated quantiles, no burn-eval crash."""
    store = SeriesStore()

    def snap(les, cums):
        samples = [{"labels": {"pool": "p", "le": le}, "value": c,
                    "name": "nns_admission_latency_seconds_bucket"}
                   for le, c in zip(les, cums)]
        return {"metrics": {"nns_admission_latency_seconds": {
            "name": "nns_admission_latency_seconds",
            "kind": "histogram", "help": "", "samples": samples}}}

    wide = ("0.001", "0.01", "0.1", "1.0", "+Inf")
    store.ingest("local", snap(wide, [0, 0, 0, 0, 0]), 1.0)
    store.ingest("local", snap(wide, [10, 20, 30, 40, 50]), 2.0)
    (_k, s), = store.match("nns_admission_latency_seconds", {})
    assert len(s.raw) == 1
    narrow = ("0.001", "0.01", "+Inf")
    store.ingest("local", snap(narrow, [5, 10, 20]), 3.0)
    assert list(s.raw) == [] and list(s.qwin) == []
    assert s.bounds == (0.001, 0.01, float("inf"))
    store.ingest("local", snap(narrow, [105, 110, 120]), 4.0)
    # quantiles derive from the NEW layout only
    assert len(s.raw) == 1 and len(s.raw[-1][1]) == 3
    assert 0 < s.last("p50")[1] <= 0.001
    assert s.hist_window(10.0, 4.0) == [100.0, 0.0, 0.0]


# -- slo_burn rules -----------------------------------------------------------


def _hist_snap(cums, pools=None):
    samples = []
    for le, c in zip(("0.001", "0.01", "0.1", "+Inf"), cums):
        samples.append({"labels": {"pool": "p", "le": le}, "value": c,
                        "name": "nns_admission_latency_seconds_bucket"})
    return {"pools": pools or [],
            "metrics": {"nns_admission_latency_seconds": {
                "name": "nns_admission_latency_seconds",
                "kind": "histogram", "help": "", "samples": samples}}}


def test_burn_histogram_mode_with_pool_slo_hint():
    """slo_ms omitted: derived from the pool's own admission slo-ms in
    the same snapshot."""
    cums = [0, 0, 0, 0]
    pools = [{"pool": "p", "admission": {"slo_ms": 10.0}}]
    w = Watch(rules=[AlertRule(name="burn", kind="slo_burn",
                               metric="nns_admission_latency_seconds",
                               fast_s=3.0, slow_s=10.0, budget=0.01,
                               burn=4.0)],
              registry=MetricsRegistry(),
              source=_src(lambda: _hist_snap(list(cums), pools)))
    now = 0.0
    for _ in range(4):  # clean: all obs under 1ms
        now += 1.0
        cums = [c + 50 for c in cums]
        assert w.sample_once(now) == []
    fired = []
    for _ in range(12):  # 50% of new obs over the 10ms SLO
        now += 1.0
        cums = [cums[0] + 10, cums[1] + 10, cums[2] + 60, cums[3] + 60]
        fired += w.sample_once(now)
    assert fired and fired[0]["rule"] == "burn"
    assert fired[0]["detail"]["err_frac"]["fast"] > 0.04


def test_burn_counter_ratio_mode_shed_over_submitted():
    shed, sub = [0.0], [0.0]

    def snap():
        return {"metrics": {
            "nns_admission_shed_total": {
                "name": "nns_admission_shed_total", "kind": "counter",
                "help": "", "samples": [{"labels": {"pool": "p",
                                                    "priority": "low"},
                                         "value": shed[0]}]},
            "nns_admission_submitted_total": {
                "name": "nns_admission_submitted_total",
                "kind": "counter", "help": "",
                "samples": [{"labels": {"pool": "p",
                                        "priority": "low"},
                             "value": sub[0]}]},
        }}

    w = Watch(rules=[AlertRule(name="shed-burn", kind="slo_burn",
                               metric="nns_admission_shed_total",
                               per="nns_admission_submitted_total",
                               fast_s=3.0, slow_s=10.0, budget=0.05,
                               burn=2.0)],
              registry=MetricsRegistry(), source=_src(snap))
    now = 0.0
    for _ in range(4):  # no sheds
        now += 1.0
        sub[0] += 100
        assert w.sample_once(now) == []
    fired = []
    for _ in range(12):  # 30% shed: err 0.3 >= 2 x 0.05 budget
        now += 1.0
        sub[0] += 100
        shed[0] += 30
        fired += w.sample_once(now)
    assert fired and fired[0]["rule"] == "shed-burn"


# -- actions ------------------------------------------------------------------


def test_alert_export_into_registry_and_top_render():
    from nnstreamer_tpu.obs.top import render

    reg = MetricsRegistry()
    state = {"v": 2.0}
    w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                               metric="nns_edge_breaker_state",
                               op=">=", value="open",
                               severity="critical")],
              registry=reg,
              source=_src(lambda: _gauge_snap(
                  "nns_edge_breaker_state", state["v"],
                  {"link": "l", "peer": "p", "kind": "edge"})))
    w.sample_once(1.0)
    w.sample_once(2.0)
    snap = reg.snapshot()
    fams = snap["metrics"]
    states = {(s["labels"]["rule"], s["labels"]["severity"]):
              s["value"] for s in fams["nns_alert_state"]["samples"]}
    assert states[("brk", "critical")] == 1.0
    fired = {s["labels"]["rule"]: s["value"]
             for s in fams["nns_alerts_fired_total"]["samples"]}
    assert fired["brk"] == 1.0
    table = render(snap)
    assert "ALERT" in table and "brk" in table and "FIRING" in table
    # resolution drops the gauge to 0 and the table shows ok
    state["v"] = 0.0
    w.sample_once(3.0)
    snap = reg.snapshot()
    states = {s["labels"]["rule"]: s["value"]
              for s in snap["metrics"]["nns_alert_state"]["samples"]}
    assert states["brk"] == 0.0
    assert "FIRING" not in render(snap)


def test_firing_alert_triggers_flightrec_once():
    from nnstreamer_tpu.obs.flightrec import FLIGHT

    FLIGHT.clear()
    state = {"v": 2.0}
    w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                               metric="nns_edge_breaker_state",
                               op=">=", value="open")],
              registry=MetricsRegistry(),
              source=_src(lambda: _gauge_snap(
                  "nns_edge_breaker_state", state["v"],
                  {"link": "l", "peer": "p", "kind": "edge"})))
    for t in (1.0, 2.0, 3.0, 4.0):  # stays firing: ONE episode
        w.sample_once(t)
    assert FLIGHT.triggers.get("alert") == 1
    kinds = [e["kind"] for e in FLIGHT.events()]
    assert "alert" in kinds
    # resolve, re-fire: a NEW episode triggers again
    state["v"] = 0.0
    w.sample_once(5.0)
    assert "alert-resolved" in [e["kind"] for e in FLIGHT.events()]
    state["v"] = 2.0
    w.sample_once(6.0)
    assert FLIGHT.triggers.get("alert") == 2
    FLIGHT.clear()


def test_firing_alert_posts_bus_warning():
    from nnstreamer_tpu.runtime.events import MessageKind

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="watch-bus")
    src = AppSrc(name="src", spec=spec, max_buffers=8)
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, sink).link(src, sink)
    warnings = []
    p.bus.add_watch(lambda m: warnings.append(m)
                    if m.kind == MessageKind.WARNING else None)
    p.start()
    try:
        state = {"v": 2.0}
        w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                                   metric="nns_edge_breaker_state",
                                   op=">=", value="open")],
                  registry=REGISTRY,
                  source=_src(lambda: _gauge_snap(
                      "nns_edge_breaker_state", state["v"],
                      {"link": "l", "peer": "p", "kind": "edge"})))
        w.sample_once(1.0)
        assert warnings and warnings[0].data["alert"] == "brk"
        assert warnings[0].source == "nns-watch"
    finally:
        src.end_of_stream()
        p.stop()


# -- kill switch --------------------------------------------------------------


def test_disabled_watch_is_strictly_inert(monkeypatch):
    from nnstreamer_tpu.obs import hooks

    monkeypatch.setattr(hooks, "DISABLED", True)
    reg = MetricsRegistry()
    w = Watch(rules=default_rules(), registry=reg,
              source=_src(lambda: _gauge_snap("nns_mfu", 1.0)))
    assert w.enabled is False
    assert w.start() is False
    assert w._thread is None
    assert w.sample_once() == []
    assert w.samples == 0
    # no export families were even created
    assert "nns_alert_state" not in reg.collect()
    assert len(w.store) == 0


# -- fleet mode ---------------------------------------------------------------


def test_fleet_mode_scrapes_endpoint_and_down_alert():
    from nnstreamer_tpu.obs.metrics import serve_metrics

    reg = MetricsRegistry()
    reg.gauge("nns_mfu", "t", labelnames=("source",)) \
        .labels(source="m").set(0.5)
    srv = reg.serve(port=0)
    try:
        # one live endpoint + one dead one
        dead = "127.0.0.1:1"
        w = Watch(rules=[AlertRule(name="never", kind="threshold",
                                   metric="nns_mfu", op=">",
                                   value=1e9)],
                  registry=MetricsRegistry(),
                  endpoints=[f"127.0.0.1:{srv.port}", dead])
        fired = []
        for i in range(Watch.DOWN_AFTER):
            fired += w.sample_once()
        assert [e["rule"] for e in fired] == ["endpoint-down"]
        assert dead in fired[0]["detail"]["endpoint"]
        # the live endpoint's series landed under ITS endpoint key
        eps = {k[0] for k in w.store._series}
        assert f"127.0.0.1:{srv.port}" in eps
    finally:
        srv.close()


def test_healthz_exposes_alert_summary():
    reg = MetricsRegistry()
    state = {"v": 2.0}
    w = Watch(rules=[AlertRule(name="brk", kind="threshold",
                               metric="nns_edge_breaker_state",
                               op=">=", value="open",
                               severity="critical")],
              registry=reg,
              source=_src(lambda: _gauge_snap(
                  "nns_edge_breaker_state", state["v"],
                  {"link": "l", "peer": "p", "kind": "edge"})))
    w.sample_once(1.0)
    srv = reg.serve(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["alerts"]["firing"] == 1
        assert doc["alerts"]["by_severity"] == {"critical": 1}
        assert doc["alerts"]["rules"] == ["brk"]
    finally:
        srv.close()


# -- CLI ----------------------------------------------------------------------


def test_nns_watch_cli_once(tmp_path):
    from nnstreamer_tpu.obs.watch import main as watch_main

    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rule": [
        {"name": "never", "kind": "threshold", "metric": "nns_mfu",
         "op": ">", "value": 1e9}]}))
    buf = io.StringIO()
    rc = watch_main(["--once", "1", "--interval", "0.01",
                     "--rules", str(rules)], out=buf)
    assert rc == 0
    assert "never" in buf.getvalue() and "ok" in buf.getvalue()
    # malformed rules exit 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert watch_main(["--once", "1", "--rules", str(bad)],
                      out=io.StringIO()) == 2


# -- satellite: registry scrape vs concurrent Pipeline.stop() ----------------


def test_registry_scrape_races_pipeline_stop():
    """snapshot() hammered from another thread while pipelines start,
    stream and stop must never raise and never lose the scrape (the
    weakref unregister can land mid-pull)."""
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    errors = []
    stop_evt = threading.Event()
    snaps = [0]

    def scraper():
        while not stop_evt.is_set():
            try:
                snap = REGISTRY.snapshot()
                assert isinstance(snap["pipelines"], list)
                snaps[0] += 1
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)
                return

    threads = [threading.Thread(target=scraper) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for round_i in range(6):
            pipes = []
            for j in range(3):
                p = Pipeline(name=f"race-{round_i}-{j}")
                src = AppSrc(name="src", spec=spec, max_buffers=20)
                q = Queue(name="q", max_size_buffers=20)
                flt = TensorFilter(name="net", framework="jax-xla",
                                   model="_t_watch")
                sink = AppSink(name="out", max_buffers=20)
                p.add(src, q, flt, sink).link(src, q, flt, sink)
                p.start()
                pipes.append((p, src, sink))
            for p, src, sink in pipes:
                from nnstreamer_tpu.core import Buffer

                for n in range(4):
                    src.push_buffer(Buffer.of(
                        np.zeros(SHAPE, np.float32), pts=n))
                src.end_of_stream()
            for p, _src, _sink in pipes:
                p.wait_eos(timeout=10, raise_on_error=False)
                p.stop()
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    assert snaps[0] > 0


def test_watch_runs_against_live_registry():
    """End-to-end: a watchdog thread sampling the real global registry
    while a pipeline streams — no crashes, series appear, no alerts
    from the default pack on a healthy pipeline."""
    from nnstreamer_tpu.core import Buffer

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="watch-live")
    src = AppSrc(name="src", spec=spec, max_buffers=70)
    q = Queue(name="q", max_size_buffers=70)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_watch")
    sink = AppSink(name="out", max_buffers=70)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    w = Watch(rules=default_rules(), interval_s=0.02)
    assert w.start() is True
    p.start()
    try:
        for n in range(64):
            src.push_buffer(Buffer.of(np.zeros(SHAPE, np.float32),
                                      pts=n))
            time.sleep(0.002)
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
    finally:
        p.stop()
        time.sleep(0.1)
        w.stop()
    assert w.samples > 3
    assert len(w.store) > 0
    assert list(w.alert_log) == [], list(w.alert_log)


# -- ISSUE-19: the [store] rules-file section ---------------------------------


def test_parse_store_section_overrides(tmp_path):
    from nnstreamer_tpu.obs.watch import (lint_store, load_store,
                                          parse_store)

    path = tmp_path / "rules.json"
    path.write_text(json.dumps({
        "rule": [{"name": "r", "kind": "threshold",
                  "metric": "nns_mfu"}],
        "store": {"ring_points": 256, "max_series": 1024}}))
    assert load_store(str(path)) == {"ring_points": 256,
                                     "max_series": 1024}
    # absent section: the Watch defaults stand
    assert parse_store({"rule": []}) == {}
    assert lint_store({}) == []
    with pytest.raises(RuleError, match="unknown key"):
        parse_store({"store": {"ring_pints": 256}})
    with pytest.raises(RuleError, match="positive integer"):
        parse_store({"store": {"ring_points": 0}})
    with pytest.raises(RuleError, match="positive integer"):
        parse_store({"store": {"max_series": True}})
    with pytest.raises(RuleError, match="table"):
        parse_store({"store": [256]})


def test_lint_store_flags_unworkable_sizing():
    from nnstreamer_tpu.obs.watch import lint_store

    probs = lint_store({"ring_points": watch_mod.QUANT_WINDOW_TICKS - 1})
    assert any("quantile window" in p for p in probs)
    probs = lint_store({"max_series": 8})
    assert any("max_series" in p for p in probs)
    assert lint_store({"ring_points": 512, "max_series": 4096}) == []


# -- ISSUE-19: rate-from-zero must not resurrect for REBORN series ------------


def test_store_reborn_series_rebases_not_rate_from_zero():
    """A series evicted (source gone for EVICT_TICKS) whose key later
    re-appears carries accumulated HISTORY, not one window's
    increments: it must re-base silently — the rate-from-zero shortcut
    (kept for genuinely new series, pinned above) would manufacture a
    giant phantom spike out of the old cumulative value."""
    store = SeriesStore()
    store.EVICT_TICKS = 2
    store.ingest("local",
                 _counter_snap("nns_edge_timeouts_total", 1000.0), 1.0)
    store.ingest("local",
                 _counter_snap("nns_edge_timeouts_total", 1010.0), 2.0)
    (_k, s), = store.match("nns_edge_timeouts_total", {})
    assert [v for _t, v in s.rings["rate"]] == [10.0]
    # the source disappears long enough to be evicted outright
    for ts in (3.0, 4.0, 5.0, 6.0):
        store.ingest("local", {"metrics": {}}, ts)
    assert len(store) == 0
    # ... then the same key returns with its big cumulative value
    store.ingest("local",
                 _counter_snap("nns_edge_timeouts_total", 1020.0), 7.0)
    (_k, s2), = store.match("nns_edge_timeouts_total", {})
    assert list(s2.rings["rate"]) == []  # re-based, no 1020/s phantom
    # and from there, honest deltas resume
    store.ingest("local",
                 _counter_snap("nns_edge_timeouts_total", 1025.0), 8.0)
    assert [v for _t, v in s2.rings["rate"]] == [5.0]


def test_store_eviction_memory_is_bounded():
    store = SeriesStore()
    store.EVICT_TICKS = 1
    store.EVICT_MEMORY = 4
    for i in range(12):
        snap = _counter_snap("nns_edge_timeouts_total", float(i),
                             {"link": str(i)})
        store.ingest("local", snap, float(i * 10))
        store.ingest("local", {"metrics": {}}, float(i * 10 + 1))
        store.ingest("local", {"metrics": {}}, float(i * 10 + 2))
        store.ingest("local", {"metrics": {}}, float(i * 10 + 3))
    assert len(store._evicted) <= 4


# -- ISSUE-19: the per= denominator label join --------------------------------


def test_ratio_denominator_joins_across_label_schemas():
    """shed{pool,priority,reason} over submitted{pool,priority}: the
    denominator lacks the numerator's `reason` label, so the exact-
    label lookup can never bind — the join must fall back to the
    denominator agreeing on the SHARED labels (this is the default
    pack's own shed-burn shape)."""
    state = {"shed": 0.0, "sub": 0.0}

    def snap():
        return {"pools": [], "metrics": {
            "nns_admission_shed_total": {
                "name": "nns_admission_shed_total", "kind": "counter",
                "help": "", "samples": [
                    {"labels": {"pool": "pl", "priority": "normal",
                                "reason": "slo"},
                     "value": state["shed"]}]},
            "nns_admission_submitted_total": {
                "name": "nns_admission_submitted_total",
                "kind": "counter", "help": "", "samples": [
                    {"labels": {"pool": "pl", "priority": "normal"},
                     "value": state["sub"]}]},
        }}

    w = Watch(rules=[AlertRule(
        name="shed-ratio", kind="threshold",
        metric="nns_admission_shed_total",
        per="nns_admission_submitted_total", op=">=", value=0.4,
        signal="rate")],
        interval_s=1.0, registry=MetricsRegistry(), source=_src(snap))
    fired = []
    for t in range(1, 6):
        state["shed"] = 10.0 * t
        state["sub"] = 20.0 * t
        fired += w.sample_once(float(t))
    assert [ev["rule"] for ev in fired] == ["shed-ratio"]
    assert fired[0]["detail"]["value"] == pytest.approx(0.5)


def test_burn_counter_ratio_binds_across_label_schemas():
    """The same join through the slo_burn path: a shed-vs-submitted
    error budget must compute even though the two families' label sets
    differ (regression for the denominator lookup that silently
    returned None)."""
    state = {"shed": 0.0, "sub": 0.0}

    def snap():
        return {"pools": [], "metrics": {
            "nns_admission_shed_total": {
                "name": "nns_admission_shed_total", "kind": "counter",
                "help": "", "samples": [
                    {"labels": {"pool": "pl", "priority": "normal",
                                "reason": "queue-full"},
                     "value": state["shed"]}]},
            "nns_admission_submitted_total": {
                "name": "nns_admission_submitted_total",
                "kind": "counter", "help": "", "samples": [
                    {"labels": {"pool": "pl", "priority": "normal"},
                     "value": state["sub"]}]},
        }}

    w = Watch(rules=[AlertRule(
        name="shed-burn", kind="slo_burn",
        metric="nns_admission_shed_total",
        per="nns_admission_submitted_total",
        budget=0.05, burn=2.0, fast_s=2.0, slow_s=4.0)],
        interval_s=1.0, registry=MetricsRegistry(), source=_src(snap))
    fired = []
    for t in range(1, 8):
        state["shed"] = 50.0 * t   # 50% of submissions shed: way past
        state["sub"] = 100.0 * t   # a 5% budget at 2x burn
        fired += w.sample_once(float(t))
    assert [ev["rule"] for ev in fired] == ["shed-burn"]
    frac = fired[0]["detail"]["err_frac"]
    assert frac["fast"] == pytest.approx(0.5)

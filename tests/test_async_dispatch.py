"""Async dispatch hot path (ISSUE 17): ordering, flush and error
contracts that must survive the filter/pool returning futures.

The single-dispatch rework makes every invoke path return jax arrays
still executing on the device; ``block_until_ready`` moved to sinks
(depth-1 pipelined fence) and sampled-stat boundaries.  These tests pin
what that is NOT allowed to break: per-stream FIFO + pts integrity on
the single-frame, micro-batch and shared-pool paths, EOS flushing a
partial window with no frame loss AND meaning "device finished", async
errors surfacing on the owning stream's bus only, donated inputs
raising ``DonatedTensorError`` on re-read, and the hot path staying
fully async (zero blocking fences) under NNS_TPU_OBS_DISABLE.
"""

import threading

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.core.buffer import DonatedTensorError, Tensor
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import (
    JaxXlaFilter,
    register_model,
    unregister_model,
)
from nnstreamer_tpu.runtime import MODEL_POOL, Pipeline

SHAPE = (4,)
SPEC = TensorsSpec.from_shapes([SHAPE], np.float32)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_async", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_async")


@pytest.fixture(autouse=True)
def _pool_clean():
    yield
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()


def _frame(stream: int, i: int) -> Buffer:
    # stream-tagged values: a demux mixup is detectable, not just an
    # ordering slip
    return Buffer.of(np.full(SHAPE, stream * 1000.0 + i, np.float32),
                     pts=i)


def _check_stream(bufs, stream: int):
    for i, b in enumerate(bufs):
        assert b.pts == i, f"stream {stream}: pts {b.pts} at slot {i}"
        np.testing.assert_allclose(
            b.tensors[0].np(),
            np.full(SHAPE, (stream * 1000.0 + i) * 2.0 + 1.0),
            err_msg=f"stream {stream} frame {i}: wrong payload")


def _pull_all(sink, n, timeout=10.0):
    out = []
    for _ in range(n):
        b = sink.pull(timeout=timeout)
        assert b is not None, f"stalled after {len(out)}/{n} buffers"
        out.append(b)
    return out


class _FakeArr:
    """Stands in for an in-flight jax array at the sink fence."""

    shape = SHAPE
    dtype = np.float32

    def __init__(self, error=None):
        self.error = error
        self.blocked = 0

    def block_until_ready(self):
        self.blocked += 1
        if self.error is not None:
            raise self.error
        return self


# -- FIFO / pts across the three dispatch paths ------------------------------


def test_single_frame_async_fifo_pts_values():
    n = 32
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_async")
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, flt, sink).link(src, flt, sink)
    with p:
        for i in range(n):
            src.push_buffer(_frame(0, i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        outs = _pull_all(sink, n)
    _check_stream(outs, 0)


def test_microbatch_fifo_and_partial_eos_flush():
    # 21 frames into batch=8: two full windows + a 5-frame remainder
    # that only EOS can flush — every frame must come out, in order,
    # already computed by the time wait_eos() returns
    n = 21
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=n + 4)
    q = Queue(name="q", max_size_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_async",
                       batch=8, batch_timeout_ms=10_000.0)
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    with p:
        for i in range(n):
            src.push_buffer(_frame(0, i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        outs = _pull_all(sink, n)
    _check_stream(outs, 0)


def _pool_pipeline(tag: str, n_bufs: int, sink_cls=AppSink):
    p = Pipeline(name=f"p_{tag}")
    src = AppSrc(name="src", spec=SPEC, max_buffers=n_bufs + 4)
    q = Queue(name="q", max_size_buffers=n_bufs + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_async",
                       batch=8, batch_timeout_ms=50.0, share_model=True)
    sink = sink_cls(name="out", max_buffers=n_bufs + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, sink


def test_shared_pool_async_fifo_per_stream():
    n_streams, n = 2, 24
    pipes = [_pool_pipeline(str(s), n) for s in range(n_streams)]
    for p, *_ in pipes:
        p.start()

    def produce(s):
        _, src, _ = pipes[s]
        for i in range(n):
            src.push_buffer(_frame(s, i))
        src.end_of_stream()

    threads = [threading.Thread(target=produce, args=(s,))
               for s in range(n_streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p, *_ in pipes:
        assert p.wait_eos(timeout=30)
    for s, (p, _, sink) in enumerate(pipes):
        _check_stream(_pull_all(sink, n), s)
        p.stop()


# -- per-owner error routing -------------------------------------------------


class _BrokenSink(AppSink):
    """A downstream that fails on every frame — the pool demux must
    route the failure to THIS stream's bus only."""

    def render(self, buf):
        raise RuntimeError("broken downstream (injected)")


def test_shared_pool_broken_downstream_errors_own_bus_only():
    n = 16
    pa, src_a, _ = _pool_pipeline("a", n, sink_cls=_BrokenSink)
    pb, src_b, sink_b = _pool_pipeline("b", n)
    pa.start()
    pb.start()
    try:
        for i in range(n):
            src_a.push_buffer(_frame(0, i))
            src_b.push_buffer(_frame(1, i))
        src_a.end_of_stream()
        src_b.end_of_stream()
        # the healthy stream finishes cleanly — its window-mates'
        # render failures must not leak onto its bus
        assert pb.wait_eos(timeout=30)
        assert pb.error is None
        _check_stream(_pull_all(sink_b, n), 1)
        assert not pa.wait_eos(timeout=10, raise_on_error=False)
        assert pa.error is not None
        assert "broken downstream" in str(pa.error)
    finally:
        pa.stop()
        pb.stop()


# -- sink fence: depth-1 pipelining + EOS drain ------------------------------


def test_sink_fence_is_depth1_pipelined():
    """Rendering buffer N fences buffer N-1's completion witness —
    never N's own (that would serialize host prep against device
    execution and kill the overlap the async path exists for)."""
    sink = AppSink(name="s", max_buffers=8)
    a1, a2, a3 = _FakeArr(), _FakeArr(), _FakeArr()
    sink.chain(None, Buffer.of(Tensor(a1)))
    assert (a1.blocked, sink._pending_fence) == (0, a1)
    sink.chain(None, Buffer.of(Tensor(a2)))
    assert (a1.blocked, a2.blocked) == (1, 0)
    sink.chain(None, Buffer.of(Tensor(a3)))
    assert (a2.blocked, a3.blocked) == (1, 0)


def test_eos_fence_surfaces_async_error_on_bus():
    """An async XLA failure still in flight when EOS arrives surfaces
    as an ERROR on the sink's bus — EOS never silently swallows a
    failed window."""
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=8)
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, sink).link(src, sink)
    with p:
        src.push_buffer(_frame(0, 0))
        assert sink.pull(timeout=10) is not None  # chain done
        with sink._fence_lock:
            sink._pending_fence = _FakeArr(
                error=RuntimeError("injected async xla error"))
        src.end_of_stream()
        assert not p.wait_eos(timeout=10, raise_on_error=False)
        assert "injected async xla error" in str(p.error)


def test_eos_drains_retained_window():
    """wait_eos() returning means the device finished every window:
    the sink's retained witness is fenced (blocked on) and cleared
    before the EOS message posts."""
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=8)
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, sink).link(src, sink)
    with p:
        src.push_buffer(_frame(0, 0))
        assert sink.pull(timeout=10) is not None
        witness = _FakeArr()
        with sink._fence_lock:
            sink._pending_fence = witness
        src.end_of_stream()
        assert p.wait_eos(timeout=10)
        assert witness.blocked == 1
        assert sink._pending_fence is None


# -- donation safety on the async paths --------------------------------------


def test_donated_input_reread_raises_microbatch():
    import jax.numpy as jnp

    n = 8
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=n + 4)
    q = Queue(name="q", max_size_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_async",
                       batch=4, batch_timeout_ms=10_000.0,
                       custom="donate")
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    held = []
    with p:
        for i in range(n):
            b = Buffer.of(jnp.full(SHAPE, float(i), jnp.float32), pts=i)
            held.append(b)
            src.push_buffer(b)
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        outs = _pull_all(sink, n)
    for i, b in enumerate(outs):
        assert b.pts == i
        np.testing.assert_allclose(b.tensors[0].np(),
                                   np.full(SHAPE, i * 2.0 + 1.0))
    # the batched dispatch donated the device-resident inputs: every
    # retained reference must fail the READ, not return reused HBM
    for b in held:
        assert b.tensors[0].is_donated
        with pytest.raises(DonatedTensorError):
            b.tensors[0].np()


# -- NNS_TPU_OBS_DISABLE: the hot path is FULLY async ------------------------


def test_hot_path_fully_async_under_obs_disable(monkeypatch):
    from nnstreamer_tpu.elements import filter as filter_mod
    from nnstreamer_tpu.obs import hooks as _hooks

    calls = []
    monkeypatch.setattr(_hooks, "DISABLED", True)
    monkeypatch.setattr(filter_mod, "block_all",
                        lambda arrs: calls.append(len(arrs)))
    n = 16
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC, max_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_async")
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, flt, sink).link(src, flt, sink)
    with p:
        for i in range(n):
            src.push_buffer(_frame(0, i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        outs = _pull_all(sink, n)
    _check_stream(outs, 0)
    # zero sampling fences, zero gate bookkeeping, zero HBM retention
    assert calls == []
    assert flt._invoke_seq == 0
    assert flt._last_out is None


def test_pool_dispatch_fully_async_under_obs_disable(monkeypatch):
    from nnstreamer_tpu.obs import hooks as _hooks
    from nnstreamer_tpu.runtime import serving as serving_mod

    calls = []
    monkeypatch.setattr(_hooks, "DISABLED", True)
    monkeypatch.setattr(serving_mod, "block_all",
                        lambda arrs: calls.append(len(arrs)))
    n = 16
    p, src, sink = _pool_pipeline("async", n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(0, i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        entry = p["net"]._pool_entry
        assert entry is not None and entry._last_out is None
        outs = _pull_all(sink, n)
    _check_stream(outs, 0)
    assert calls == []

"""pbtxt ⇄ launch converter (dev-tooling parity:
/root/reference/tools/development/parser/ — the flex/bison gst⇄pbtxt
converter)."""

import os

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def conv():
    from nnstreamer_tpu.tools import pipeline_convert

    return pipeline_convert


LINEAR = ("appsrc name=src ! tensor_transform name=t mode=arithmetic "
          "option=typecast:float32,div:255.0 ! tensor_sink name=out")


class TestLaunchToPbtxt:
    def test_linear_chain(self, conv):
        pb = conv.launch_to_pbtxt(LINEAR)
        assert 'input_stream: "src"' in pb          # graph-level source
        assert 'output_stream: "out"' in pb         # graph-level sink
        assert 'calculator: "tensor_transformCalculator"' in pb
        # node_options carries non-default properties (the reference's
        # open TODO, convert.c "Filling 'node_options'")
        assert 'mode: "arithmetic"' in pb
        assert 'option: "typecast:float32,div:255.0"' in pb
        # stream naming: transform consumes src's stream
        assert 'input_stream: "src"' in pb.split("node: {")[2]

    def test_branched_graph(self, conv):
        pb = conv.launch_to_pbtxt(
            "tensor_mux name=m sync-mode=nosync ! tensor_sink name=out "
            "appsrc name=a ! m.sink_0  appsrc name=b ! m.sink_1")
        # both sources appear as graph inputs and mux input streams
        assert 'input_stream: "a"' in pb and 'input_stream: "b"' in pb
        mux_block = next(b for b in pb.split("node: {")
                         if "tensor_muxCalculator" in b)
        assert "a:sink_0" in mux_block and "b:sink_1" in mux_block


class TestRoundTrip:
    def test_linear_round_trip_runs(self, conv):
        """launch → pbtxt → launch must yield a RUNNABLE pipeline with
        the same topology and properties."""
        from nnstreamer_tpu.core import Buffer, TensorsSpec
        from nnstreamer_tpu.runtime.parser import parse_launch

        launch2 = conv.pbtxt_to_launch(conv.launch_to_pbtxt(LINEAR))
        p = parse_launch(launch2)
        assert set(p.elements) == {"src", "t", "out"}
        assert p["t"].mode == "arithmetic"
        assert p["t"].option == "typecast:float32,div:255.0"
        got = []
        p["out"].connect(lambda b: got.append(float(b.tensors[0].np().max())))
        p["src"].spec = TensorsSpec.parse("4:1", "uint8")
        with p:
            p["src"].push_buffer(Buffer.of(
                np.full((1, 4), 255, np.uint8)))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=30)
        assert got == [1.0]

    def test_branched_round_trip_topology(self, conv):
        from nnstreamer_tpu.runtime.parser import parse_launch

        src = ("tensor_mux name=m sync-mode=nosync ! tensor_sink name=out "
               "appsrc name=a ! m.sink_0  appsrc name=b ! m.sink_1")
        launch2 = conv.pbtxt_to_launch(conv.launch_to_pbtxt(src))
        p = parse_launch(launch2)
        assert set(p.elements) == {"m", "out", "a", "b"}
        m = p["m"]
        feeders = sorted(pad.peer.element.name for pad in m.sinkpads
                         if pad.peer is not None)
        assert feeders == ["a", "b"]
        assert m.srcpads[0].peer.element.name == "out"

    def test_pbtxt_errors(self, conv):
        with pytest.raises(ValueError):
            conv.pbtxt_to_launch('node: { name: "x" }')  # no calculator
        with pytest.raises(ValueError):
            conv.pbtxt_to_launch(
                'node: { calculator: "fooCalculator" name: "f" '
                'input_stream: "ghost" }')  # unknown stream source

"""`obs/forecast.py` + the `forecast` watch-rule kind (ISSUE-19
surface).

The robust trend fit (Theil–Sen slope, median-projected level, MAD
band), crossing prediction (ETA within one tick on a clean ramp, flat
series never firing, already-over staying reactive territory, the
noise gate suppressing insignificant slopes, re-convergence after a
step), capacity headroom (MFU path, occupancy fallback, scale-out
clamp), the FORECASTS store, the watch integration (rule grammar,
horizon refusal, histogram skip, `nns_forecast_*` gauges, the firing
transition), the per-pool capacity tick + `/healthz` summary, the
snapshot-v9 `forecasts` table, and the nns-top FORECAST section."""

import json

import pytest

from nnstreamer_tpu.obs import forecast as fc
from nnstreamer_tpu.obs.forecast import (FORECASTS, Forecasts, TrendFit,
                                         capacity_headroom,
                                         fit_trend, forecast_crossing)
from nnstreamer_tpu.obs.metrics import (MetricsRegistry, REGISTRY,
                                        capacity_health)
from nnstreamer_tpu.obs.watch import (AlertRule, RuleError, Watch,
                                      parse_rules)


@pytest.fixture(autouse=True)
def _clean_forecasts():
    FORECASTS.reset()
    yield
    FORECASTS.reset()


def _gauge_snap(name, value, labels=None, pools=None):
    return {"pools": pools or [],
            "metrics": {name: {"name": name, "kind": "gauge",
                               "help": "",
                               "samples": [{"labels": labels or {},
                                            "value": value}]}}}


def _counter_snap(name, value, labels=None, pools=None):
    snap = _gauge_snap(name, value, labels, pools)
    snap["metrics"][name]["kind"] = "counter"
    return snap


def _src(snap_fn):
    return lambda: [{"endpoint": "local", "snap": snap_fn(),
                     "error": None}]


# -- fit_trend ----------------------------------------------------------------


def test_fit_trend_recovers_clean_ramp():
    pts = [(float(t), 3.0 * t + 7.0) for t in range(10)]
    fit = fit_trend(pts)
    assert fit.slope == pytest.approx(3.0)
    assert fit.level == pytest.approx(3.0 * 9 + 7.0)
    assert fit.sigma == pytest.approx(0.0)
    assert fit.n == 10 and fit.t_last == 9.0
    assert fit.at(5.0) == pytest.approx(fit.level + 15.0)


def test_fit_trend_needs_history():
    assert fit_trend([]) is None
    assert fit_trend([(float(t), 1.0)
                      for t in range(fc.MIN_FIT_POINTS - 1)]) is None
    # all points on one timestamp: no pairwise slope exists
    assert fit_trend([(1.0, float(v)) for v in range(8)]) is None


def test_theil_sen_shrugs_off_outliers():
    """A third of the points being garbage moves neither the slope nor
    the level materially — the property the whole predictive layer
    leans on."""
    pts = [(float(t), 2.0 * t) for t in range(12)]
    pts[3] = (3.0, 500.0)
    pts[7] = (7.0, -300.0)
    pts[10] = (10.0, 999.0)
    fit = fit_trend(pts)
    assert fit.slope == pytest.approx(2.0, rel=0.15)
    assert abs(fit.level - 22.0) < 4.0


def test_fit_trend_caps_window():
    pts = [(float(t), float(t)) for t in range(200)]
    assert fit_trend(pts).n == fc.MAX_FIT_POINTS
    assert fit_trend(pts, max_points=8).n == 8


# -- forecast_crossing --------------------------------------------------------


def test_crossing_eta_within_one_tick():
    """Ramp at 1 unit/s sampled at 1 Hz, threshold 10 units ahead: the
    ETA lands within one sampling tick of the true crossing."""
    pts = [(float(t), float(t)) for t in range(8)]
    fit = fit_trend(pts)
    predicted, eta, firing = forecast_crossing(fit, 17.0, ">=", 20.0)
    assert firing
    assert eta == pytest.approx(10.0, abs=1.0)
    assert predicted == pytest.approx(27.0)


def test_already_over_is_reactive_territory():
    fit = TrendFit(slope=1.0, level=50.0, sigma=0.0, n=8, t_last=0.0)
    predicted, eta, firing = forecast_crossing(fit, 40.0, ">=", 10.0)
    assert (eta, firing) == (0.0, False)
    assert predicted == pytest.approx(60.0)


def test_flat_series_never_fires():
    fit = TrendFit(slope=0.0, level=5.0, sigma=0.3, n=16, t_last=0.0)
    predicted, eta, firing = forecast_crossing(fit, 10.0, ">=", 30.0)
    assert (eta, firing) == (None, False)
    assert predicted == pytest.approx(5.0)


def test_trending_away_never_fires():
    fit = TrendFit(slope=-2.0, level=5.0, sigma=0.0, n=8, t_last=0.0)
    _p, eta, firing = forecast_crossing(fit, 10.0, ">=", 30.0)
    assert (eta, firing) == (None, False)
    # the mirror direction: rising series against a "<" rule
    fit = TrendFit(slope=2.0, level=5.0, sigma=0.0, n=8, t_last=0.0)
    _p, eta, firing = forecast_crossing(fit, 1.0, "<=", 30.0)
    assert (eta, firing) == (None, False)


def test_mad_gate_suppresses_insignificant_trend():
    """A slope buried in the residual noise band must not fire even
    when its extrapolation crosses inside the horizon — this is the
    zero-false-positive property the capacity bench pins end to end."""
    noise = [0.0, 5.0, -5.0, 3.0, -4.0, 4.0, -3.0, 2.0] * 2
    pts = [(float(t), 0.02 * t + noise[t]) for t in range(16)]
    fit = fit_trend(pts)
    sig = abs(fit.slope) * 30.0
    assert sig <= fc.SIGNIFICANCE_SIGMAS * fit.sigma
    _p, _eta, firing = forecast_crossing(fit, fit.level + 0.1, ">=",
                                         30.0)
    assert not firing
    # the same geometry with the noise stripped IS significant
    clean = fit_trend([(float(t), 0.02 * t) for t in range(16)])
    _p, _eta, firing = forecast_crossing(clean, clean.level + 0.1,
                                         ">=", 30.0)
    assert firing


def test_step_reconverges_to_quiet():
    """A level step looks like a ramp only while the window straddles
    it; once the fit window is all post-step, slope is 0 again and the
    forecast goes quiet instead of chasing the step forever."""
    series = [(float(t), 0.0 if t < 10 else 100.0) for t in range(30)]
    fit = fit_trend(series[-16:])
    assert fit.slope == pytest.approx(0.0)
    _p, _eta, firing = forecast_crossing(fit, 500.0, ">=", 30.0)
    assert not firing


# -- capacity_headroom --------------------------------------------------------


def test_capacity_headroom_mfu_path():
    cap = capacity_headroom(100.0, 150.0, mfu=0.2, mfu_ceiling=0.4)
    assert cap["sustainable_fps"] == pytest.approx(200.0)
    assert cap["headroom"] == pytest.approx(0.25)


def test_capacity_headroom_occupancy_fallback_and_clamps():
    cap = capacity_headroom(100.0, 100.0, occupancy=0.5)
    assert cap["sustainable_fps"] == pytest.approx(200.0)
    assert cap["headroom"] == pytest.approx(0.5)
    # an idling pool does not promise 1000x its current rate
    cap = capacity_headroom(10.0, 10.0, mfu=1e-4, mfu_ceiling=0.5)
    assert cap["sustainable_fps"] == pytest.approx(
        10.0 * fc.MAX_SCALE_OUT)
    # predicted overload clamps at -1, not minus-infinity
    cap = capacity_headroom(100.0, 1e6, occupancy=1.0)
    assert cap["headroom"] == -1.0


def test_capacity_headroom_refuses_blind_claims():
    assert capacity_headroom(0.0, 10.0, occupancy=0.5) is None
    assert capacity_headroom(100.0, 10.0) is None
    assert capacity_headroom(100.0, 10.0, mfu=0.0,
                             mfu_ceiling=0.4) is None


# -- the FORECASTS store ------------------------------------------------------


def test_forecasts_store_sorted_snapshot_and_reset():
    st = Forecasts()
    st.update("zz", {"rule": "zz", "firing": False})
    st.update("aa", {"rule": "aa", "firing": True})
    st.update_capacity("pool-b", {"pool": "pool-b", "headroom": 0.5})
    snap = st.snapshot()
    assert [r["rule"] for r in snap["rules"]] == ["aa", "zz"]
    assert snap["capacity"][0]["pool"] == "pool-b"
    # snapshot hands out copies, not live rows
    snap["rules"][0]["firing"] = "mutated"
    assert st.snapshot()["rules"][0]["firing"] is True
    st.reset()
    assert st.snapshot() == {"rules": [], "capacity": []}


# -- rule grammar -------------------------------------------------------------


def test_forecast_rule_grammar_parses_horizon():
    rules = parse_rules({"rule": [
        {"name": "surge", "kind": "forecast",
         "metric": "nns_pool_frames_total", "op": ">=",
         "value": 100.0, "horizon": "30s", "for": "2s"}]})
    assert rules[0].horizon_s == 30.0 and rules[0].for_s == 2.0


def test_forecast_rule_rejects_unordered_op():
    with pytest.raises(RuleError, match="ordered op"):
        AlertRule(name="r", kind="forecast", metric="nns_queue_depth",
                  op="==", value=1.0, horizon_s=30.0)


def test_watch_refuses_horizonless_forecast():
    """Parse stays lenient (nns-lint reports NNS517 at review time);
    the LIVE watchdog refuses to run a forecast with nothing to
    predict across."""
    rule = AlertRule(name="r", kind="forecast",
                     metric="nns_queue_depth", op=">=", value=1.0)
    with pytest.raises(RuleError, match="horizon"):
        Watch(rules=[rule], registry=MetricsRegistry(),
              source=_src(lambda: {"metrics": {}}))


# -- the watch integration ----------------------------------------------------


def test_forecast_rule_fires_ahead_with_eta_and_gauges():
    """A gauge ramping 2 units/s against threshold 60 with a 15 s
    horizon: the rule must fire exactly when the crossing enters the
    horizon (level 30, 15 s early — the predictive lead), publish the
    predicted value + ETA through `nns_forecast_*`, and flip the
    FORECASTS row to firing."""
    state = {"t": 0.0}
    reg = MetricsRegistry()
    rule = AlertRule(name="qd-surge", kind="forecast",
                     metric="nns_queue_depth", op=">=", value=60.0,
                     horizon_s=15.0)
    w = Watch(rules=[rule], interval_s=1.0, registry=reg,
              source=_src(lambda: _gauge_snap(
                  "nns_queue_depth", 2.0 * state["t"],
                  {"element": "q", "pipeline": "p"})))
    fired = []
    for t in range(1, 21):
        state["t"] = float(t)
        fired += [(t, ev) for ev in w.sample_once(float(t))]
        if t == 10:
            # inside the ramp but outside the horizon: exporting, not
            # firing (eta = (60 - 20)/2 = 20 s > 15 s)
            row = FORECASTS.snapshot()["rules"][0]
            assert not row["firing"]
            assert row["eta_s"] == pytest.approx(20.0, abs=1.0)
    assert [t for t, _ev in fired] == [15]
    detail = fired[0][1]["detail"]
    assert detail["eta_s"] == pytest.approx(15.0, abs=1.0)
    assert detail["value"] == pytest.approx(60.0, abs=2.0)
    assert detail["horizon_s"] == 15.0
    snap = reg.snapshot()["metrics"]
    (v,) = snap["nns_forecast_value"]["samples"]
    assert v["labels"] == {"rule": "qd-surge"}
    (eta,) = snap["nns_forecast_eta_seconds"]["samples"]
    assert eta["value"] <= 15.0
    assert FORECASTS.snapshot()["rules"][0]["firing"]


def test_forecast_rule_skips_histogram_series():
    """A forecast bound to a histogram family exports nothing and
    never fires (windowed quantiles re-derive each tick — NNS517
    catches the rule at review time; the evaluator just declines)."""
    def snap():
        samples = []
        for le, c in zip(("0.001", "0.01", "+Inf"), (50, 100, 100)):
            samples.append({"labels": {"pool": "p", "le": le},
                            "value": c,
                            "name": "nns_admission_latency_seconds_bucket"})
        return {"metrics": {"nns_admission_latency_seconds": {
            "name": "nns_admission_latency_seconds",
            "kind": "histogram", "help": "", "samples": samples}}}

    rule = AlertRule(name="h", kind="forecast",
                     metric="nns_admission_latency_seconds", op=">=",
                     value=0.5, horizon_s=30.0)
    w = Watch(rules=[rule], interval_s=1.0, registry=MetricsRegistry(),
              source=_src(snap))
    for t in range(1, 12):
        assert w.sample_once(float(t)) == []
    assert FORECASTS.snapshot()["rules"] == []


def test_capacity_tick_joins_headroom_and_healthz():
    """The per-pool capacity join: a pool pushing a flat 100 frames/s
    at 50% window occupancy sustains ~200 fps — headroom 0.5 through
    the gauge, the FORECASTS capacity row, and `/healthz`'s summary."""
    state = {"t": 0.0}

    def snap():
        pools = [{"pool": "pl", "model": None,
                  "stats": {"avg_batch_occupancy": 4.0},
                  "batcher": {"max_batch": 8}}]
        return _counter_snap("nns_pool_frames_total",
                             100.0 * state["t"], {"pool": "pl"},
                             pools=pools)

    reg = MetricsRegistry()
    w = Watch(rules=[], interval_s=1.0, registry=reg,
              source=_src(snap))
    for t in range(1, 8):
        state["t"] = float(t)
        w.sample_once(float(t))
    (row,) = FORECASTS.snapshot()["capacity"]
    assert row["pool"] == "pl"
    assert row["arrival_fps"] == pytest.approx(100.0)
    assert row["predicted_fps"] == pytest.approx(100.0, rel=0.05)
    assert row["sustainable_fps"] == pytest.approx(200.0)
    assert row["headroom"] == pytest.approx(0.5, abs=0.05)
    # with no forecast rules the default headroom horizon stands
    assert row["horizon_s"] == fc.HEADROOM_HORIZON_S
    (g,) = reg.snapshot()["metrics"]["nns_capacity_headroom"]["samples"]
    assert g["labels"] == {"pool": "pl"}
    assert g["value"] == pytest.approx(0.5, abs=0.05)
    health = capacity_health()
    assert health["pools"] == 1 and health["at_risk"] == []
    assert health["min_headroom"] == pytest.approx(0.5, abs=0.05)


def test_capacity_health_flags_predicted_overload():
    FORECASTS.update_capacity("hot", {"pool": "hot", "headroom": -0.2})
    FORECASTS.update_capacity("cold", {"pool": "cold", "headroom": 0.9})
    health = capacity_health()
    assert health == {"pools": 2, "min_headroom": -0.2,
                      "at_risk": ["hot"]}
    FORECASTS.reset()
    assert capacity_health() == {"pools": 0, "min_headroom": None,
                                 "at_risk": []}


# -- snapshot v9 + nns-top ----------------------------------------------------


def test_snapshot_v9_carries_forecasts_table():
    FORECASTS.update("surge", {
        "rule": "surge", "metric": "nns_pool_frames_total",
        "signal": "rate", "series": {}, "endpoint": "local",
        "value": 120.0, "eta_s": 4.0, "threshold": 100.0, "op": ">=",
        "horizon_s": 30.0, "slope": 2.0, "sigma": 0.1, "firing": True})
    FORECASTS.update_capacity("pl", {
        "pool": "pl", "endpoint": "local", "arrival_fps": 90.0,
        "predicted_fps": 120.0, "horizon_s": 30.0,
        "sustainable_fps": 110.0, "headroom": -0.09})
    snap = REGISTRY.snapshot()
    assert snap["version"] == 10
    assert [r["rule"] for r in snap["forecasts"]["rules"]] == ["surge"]
    assert snap["forecasts"]["capacity"][0]["pool"] == "pl"
    json.dumps(snap["forecasts"])  # wire-safe


def test_top_forecast_section_renders():
    from nnstreamer_tpu.obs.top import render

    FORECASTS.update("surge", {
        "rule": "surge", "metric": "nns_pool_frames_total",
        "signal": "rate", "series": {}, "endpoint": "local",
        "value": 120.0, "eta_s": 4.0, "threshold": 100.0, "op": ">=",
        "horizon_s": 30.0, "slope": 2.0, "sigma": 0.1, "firing": True})
    FORECASTS.update_capacity("pl", {
        "pool": "pl", "endpoint": "local", "arrival_fps": 90.0,
        "predicted_fps": 120.0, "horizon_s": 30.0,
        "sustainable_fps": 110.0, "headroom": -0.09})
    out = render(REGISTRY.snapshot())
    assert "FORECAST" in out and "surge" in out and "FIRING" in out
    assert "capacity" in out and "-9%" in out

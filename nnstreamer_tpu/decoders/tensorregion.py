"""``tensor_region`` decoder: detections → crop-region stream for
tensor_crop.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-tensorregion.c (788 LoC): consumes detection-model output and
emits a *flexible* tensor of crop coordinates (x, y, w, h in pixels of the
target frame) that tensor_crop's ``sink_info`` pad consumes — the
detect-then-crop cascade pattern.

- option1 — number of regions to emit (top-N by score; default 1)
- option2 — label file (restricts regions to labeled classes)
- option3 — target frame size ``WIDTH:HEIGHT`` (pixel coords; default
  model-normalized 300:300)

Input layout: the post-processed 4-tensor SSD layout (boxes, classes,
scores, count) or raw (loc, cls) mobilenet-ssd output.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import (
    Buffer,
    Caps,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
)
from . import Decoder, register_decoder
from .boundingbox import BoundingBoxes


@register_decoder
class TensorRegion(Decoder):
    MODE = "tensor_region"

    def __init__(self):
        super().__init__()
        self.num_regions = 1
        self.frame_w, self.frame_h = 300, 300
        self._bb = BoundingBoxes()

    def options_updated(self) -> None:
        if self.options[0]:
            self.num_regions = int(self.options[0])
        if self.options[1]:
            self._bb.set_option(1, self.options[1])
        if self.options[2]:
            w, _, h = self.options[2].partition(":")
            self.frame_w, self.frame_h = int(w), int(h or w)

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.from_spec(TensorsSpec(
            format=TensorFormat.FLEXIBLE, rate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        if buf.num_tensors >= 3:
            dets = self._bb._decode_ssd_postprocess(buf)
        else:
            dets = self._bb._decode_mobilenet_ssd(buf)
        dets.sort(key=lambda d: -d.score)
        dets = dets[:self.num_regions]
        regions = np.zeros((max(len(dets), 1), 4), np.uint32)
        for i, d in enumerate(dets):
            regions[i] = (
                int(np.clip(d.x, 0, 1) * self.frame_w),
                int(np.clip(d.y, 0, 1) * self.frame_h),
                max(int(d.w * self.frame_w), 1),
                max(int(d.h * self.frame_h), 1))
        if not dets:  # no detection: whole-frame region
            regions[0] = (0, 0, self.frame_w, self.frame_h)
        out = Buffer(
            tensors=[Tensor(regions,
                            TensorSpec.from_shape(regions.shape, np.uint32))],
            pts=buf.pts, duration=buf.duration,
            format=TensorFormat.FLEXIBLE, meta=dict(buf.meta))
        out.meta["detections"] = dets
        return out

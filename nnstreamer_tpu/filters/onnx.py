"""``onnx`` filter framework: .onnx files through XLA.

Parity target: the reference's onnxruntime sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_onnxruntime.cc:471 registers framework "onnxruntime";
tests/nnstreamer_filter_onnxruntime/runTest.sh drives the in-tree
mobilenet_v2_quant.onnx to the label "orange").  Here the model file
is *imported* rather than run through ORT (filters/onnx_import.py):
the graph — including its QLinear quantized operator set — compiles
into one XLA program with uint8-resident weights, inheriting async
invoke, hot reload, sharing and mesh placement from the jax-xla
execution machinery.

``custom=qmode:<bf16|dequant|int8|float>`` selects the quantized execution
mode (onnx_import module doc).
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..core import TensorsSpec
from .api import FilterError
from .jax_xla import JaxXlaFilter, ModelDef
from .registry import register_filter


@register_filter
class OnnxFilter(JaxXlaFilter):
    NAME = "onnx"
    ACCELERATORS = ("tpu", "cpu")

    def _load_file(self, path: str) -> ModelDef:
        ext = os.path.splitext(path)[1].lower()
        if ext != ".onnx":
            return super()._load_file(path)
        from .onnx_import import OnnxModel, build_fn

        from .importer_util import parse_custom_prop

        qmode = parse_custom_prop(self.props.custom, "qmode", "bf16")
        try:
            fn, weights, in_shape, in_dtype = build_fn(
                OnnxModel(path), qmode=qmode)
        except (ValueError, NotImplementedError, IndexError, KeyError,
                struct.error) as e:
            raise FilterError(f"onnx: {path}: {e}") from e
        in_spec = TensorsSpec.from_shapes([in_shape], np.dtype(in_dtype))
        # weights ride as a params pytree (device-placed by the jax-xla
        # machinery), not baked into the HLO as literals
        return ModelDef(fn, weights, in_spec, name=path)


@register_filter
class OnnxRuntimeAlias(OnnxFilter):
    """Alias: the reference's framework name for the same engine, so
    reference pipeline strings run unchanged."""

    NAME = "onnxruntime"

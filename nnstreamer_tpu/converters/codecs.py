"""Wire codecs shared by the converter (decode) and decoder (encode)
sub-plugins: FlexBuffers, FlatBuffers, and protobuf tensor frames.

Parity targets:
- flexbuf map layout — /root/reference/ext/nnstreamer/tensor_converter/
  tensor_converter_flexbuf.cc:23-36 (keys ``num_tensors``/``rate_n``/
  ``rate_d``/``format``/``tensor_#``; per-tensor vector of
  [name, type, dims, blob]).
- flatbuf schema — /root/reference/ext/nnstreamer/include/nnstreamer.fbs
  (``Tensors`` root table: num_tensor, frame_rate struct, [Tensor],
  format; ``Tensor``: name, type, [uint32] dimension, [ubyte] data).
- protobuf schema — /root/reference/ext/nnstreamer/include/
  nnstreamer.proto (same logical layout; field numbers are the wire
  contract and are kept identical so payloads interoperate).

The dtype enum on all three wires is the reference's ``Tensor_type``
ordering, which :class:`~nnstreamer_tpu.core.types.DType` preserves —
``int(DType)`` IS the wire value.  Dimensions travel in nnstreamer dim
order (innermost-first), converted at the edges via
``dims_to_shape``/``shape_to_dims``.

The protobuf codec is hand-rolled proto3 wire format (varints +
length-delimited fields) rather than generated code, so the schema file
and protoc stay out of the runtime; it accepts packed and unpacked
repeated dimensions.  A C++ mirror of these hot host-side loops lives in
``native/`` (loaded via ctypes when built).
"""

from __future__ import annotations

import threading
from fractions import Fraction
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core import (
    Buffer,
    DType,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    shape_to_dims,
)

__all__ = [
    "flexbuf_encode", "flexbuf_decode",
    "flatbuf_encode", "flatbuf_decode",
    "protobuf_encode", "protobuf_decode",
]


def _frame(buf: Buffer, spec: Optional[TensorsSpec]):
    """(arrays, names, rate, format) for one outgoing buffer."""
    arrays = [t.np() for t in buf.tensors]
    names = []
    for i, t in enumerate(buf.tensors):
        sp = t.spec
        names.append(sp.name or "")
    rate = spec.rate if spec is not None and spec.rate else Fraction(0, 1)
    fmt = buf.format if buf.format is not None else TensorFormat.STATIC
    return arrays, names, rate, fmt


def _rebuild(arrays: List[np.ndarray], names: List[str], rate_n: int,
             rate_d: int, fmt: int) -> Tuple[Buffer, TensorsSpec]:
    tensors = []
    for arr, nm in zip(arrays, names):
        sp = TensorSpec(dtype=DType.from_np(arr.dtype),
                        dims=shape_to_dims(arr.shape), name=nm or None)
        tensors.append(Tensor(arr, sp))
    rate = Fraction(rate_n, rate_d) if rate_d else Fraction(0, 1)
    spec = TensorsSpec.of(*[t.spec for t in tensors],
                          format=TensorFormat(fmt), rate=rate)
    return Buffer(tensors=tensors, format=TensorFormat(fmt)), spec


def _wire_dims(arr: np.ndarray) -> Sequence[int]:
    # The reference writers always emit RANK_LIMIT (16) entries, zero-
    # filled beyond the rank, and its readers unconditionally read all 16
    # (e.g. tensor_converter_flatbuf.cc:121) — pad for interop.
    dims = list(shape_to_dims(arr.shape))
    return dims + [0] * (16 - len(dims))


def _np_from_wire(dtype_val: int, dims: Sequence[int],
                  payload: bytes) -> np.ndarray:
    dt = DType(dtype_val)
    shape = tuple(reversed([d for d in dims if d > 0])) or (0,)
    n = int(np.prod(shape)) if shape else 0
    arr = np.frombuffer(payload, dtype=dt.np_dtype, count=n)
    return arr.reshape(shape)


# flatbuffers is imported lazily so the protobuf codec and everything
# upstream of it (decoder lookup, elements) keeps working without it.

def _flexbuffers():
    from flatbuffers import flexbuffers

    return flexbuffers


def _flatbuffers():
    import flatbuffers
    from flatbuffers import number_types

    return flatbuffers, number_types


# -- FlexBuffers -------------------------------------------------------------

def flexbuf_encode(buf: Buffer, spec: Optional[TensorsSpec] = None) -> bytes:
    flexbuffers = _flexbuffers()
    arrays, names, rate, fmt = _frame(buf, spec)
    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Key("num_tensors")
        fbb.UInt(len(arrays))
        fbb.Key("rate_n")
        fbb.Int(int(rate.numerator))
        fbb.Key("rate_d")
        fbb.Int(int(rate.denominator))
        fbb.Key("format")
        fbb.Int(int(fmt.value))
        for i, (arr, nm) in enumerate(zip(arrays, names)):
            fbb.Key(f"tensor_{i}")
            with fbb.Vector():
                fbb.String(nm)
                fbb.Int(int(DType.from_np(arr.dtype).value))
                fbb.TypedVectorFromElements(
                    [int(d) for d in _wire_dims(arr)])
                fbb.Blob(np.ascontiguousarray(arr).tobytes())
    return bytes(fbb.Finish())


def flexbuf_decode(data: bytes) -> Tuple[Buffer, TensorsSpec]:
    flexbuffers = _flexbuffers()
    m = flexbuffers.GetRoot(bytes(data)).AsMap
    num = m["num_tensors"].AsInt
    rate_n, rate_d = m["rate_n"].AsInt, m["rate_d"].AsInt
    try:
        fmt = m["format"].AsInt
    except KeyError:
        fmt = int(TensorFormat.STATIC.value)
    arrays, names = [], []
    for i in range(num):
        tv = m[f"tensor_{i}"].AsVector
        names.append(tv[0].AsString)
        arrays.append(_np_from_wire(
            tv[1].AsInt, [d.AsInt for d in tv[2].AsTypedVector],
            bytes(tv[3].AsBlob)))
    return _rebuild(arrays, names, rate_n, rate_d, fmt)


# -- FlatBuffers (hand-built tables; no flatc/codegen) -----------------------

_T_NAME, _T_TYPE, _T_DIMS, _T_DATA = 0, 1, 2, 3           # Tensor slots
_TS_NUM, _TS_FR, _TS_VEC, _TS_FMT = 0, 1, 2, 3            # Tensors slots
_NNS_END = 11  # Tensor_type default in nnstreamer.fbs


def flatbuf_encode(buf: Buffer, spec: Optional[TensorsSpec] = None) -> bytes:
    flatbuffers, _N = _flatbuffers()
    arrays, names, rate, fmt = _frame(buf, spec)
    b = flatbuffers.Builder(1024)
    tensor_offs = []
    for arr, nm in zip(arrays, names):
        name_off = b.CreateString(nm)
        data_off = b.CreateByteVector(np.ascontiguousarray(arr).tobytes())
        dims = [int(d) for d in _wire_dims(arr)]
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(d)
        dims_off = b.EndVector()
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(_T_NAME, name_off, 0)
        b.PrependInt32Slot(_T_TYPE, int(DType.from_np(arr.dtype).value),
                           _NNS_END)
        b.PrependUOffsetTRelativeSlot(_T_DIMS, dims_off, 0)
        b.PrependUOffsetTRelativeSlot(_T_DATA, data_off, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()
    b.StartObject(4)
    b.PrependInt32Slot(_TS_NUM, len(arrays), 0)
    b.Prep(4, 8)
    b.PrependInt32(int(rate.denominator))
    b.PrependInt32(int(rate.numerator))
    b.PrependStructSlot(_TS_FR, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(_TS_VEC, vec_off, 0)
    b.PrependInt32Slot(_TS_FMT, int(fmt.value), 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def _fb_slot(k: int) -> int:
    return 4 + 2 * k


def flatbuf_decode(data: bytes) -> Tuple[Buffer, TensorsSpec]:
    flatbuffers, _N = _flatbuffers()
    buf = bytes(data)
    pos = flatbuffers.encode.Get(flatbuffers.packer.uoffset, buf, 0)
    tab = flatbuffers.table.Table(buf, pos)
    o = tab.Offset(_fb_slot(_TS_NUM))
    num = tab.Get(_N.Int32Flags, o + tab.Pos) if o else 0
    o = tab.Offset(_fb_slot(_TS_FR))
    rate_n = rate_d = 0
    if o:
        rate_n = tab.Get(_N.Int32Flags, o + tab.Pos)
        rate_d = tab.Get(_N.Int32Flags, o + tab.Pos + 4)
    o = tab.Offset(_fb_slot(_TS_FMT))
    fmt = tab.Get(_N.Int32Flags, o + tab.Pos) if o \
        else int(TensorFormat.STATIC.value)
    arrays, names = [], []
    o = tab.Offset(_fb_slot(_TS_VEC))
    if o:
        vec = tab.Vector(o)
        for i in range(min(num, tab.VectorLen(o))):
            tt = flatbuffers.table.Table(buf, tab.Indirect(vec + 4 * i))
            no = tt.Offset(_fb_slot(_T_NAME))
            names.append(
                tt.String(no + tt.Pos).decode() if no else "")
            ty = tt.Offset(_fb_slot(_T_TYPE))
            ty = tt.Get(_N.Int32Flags, ty + tt.Pos) if ty else _NNS_END
            do = tt.Offset(_fb_slot(_T_DIMS))
            dims = []
            if do:
                dv = tt.Vector(do)
                dims = [tt.Get(_N.Uint32Flags, dv + 4 * j)
                        for j in range(tt.VectorLen(do))]
            po = tt.Offset(_fb_slot(_T_DATA))
            payload = b""
            if po:
                pv, pn = tt.Vector(po), tt.VectorLen(po)
                payload = buf[pv:pv + pn]
            arrays.append(_np_from_wire(ty, dims, payload))
    return _rebuild(arrays, names, rate_n, rate_d, fmt)


# -- protobuf (hand-rolled proto3 wire; field numbers = nnstreamer.proto) ----

def _varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    v = shift = 0
    while True:
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def protobuf_encode(buf: Buffer, spec: Optional[TensorsSpec] = None) -> bytes:
    arrays, names, rate, fmt = _frame(buf, spec)
    native = _native_encode(arrays, names, rate, fmt)
    if native is not None:
        return native
    out = bytearray()
    out += _tag(1, 0) + _varint(len(arrays))                  # num_tensor
    fr = _tag(1, 0) + _varint(int(rate.numerator)) \
        + _tag(2, 0) + _varint(int(rate.denominator))
    out += _ld(2, fr)                                         # fr
    for arr, nm in zip(arrays, names):                        # tensor
        t = bytearray()
        if nm:
            t += _ld(1, nm.encode())
        t += _tag(2, 0) + _varint(int(DType.from_np(arr.dtype).value))
        dims = b"".join(_varint(int(d)) for d in _wire_dims(arr))
        t += _ld(3, dims)                                     # packed dims
        t += _ld(4, np.ascontiguousarray(arr).tobytes())
        out += _ld(3, bytes(t))
    if int(fmt.value):
        out += _tag(4, 0) + _varint(int(fmt.value))           # format
    return bytes(out)


def _skip(data: bytes, i: int, wire: int) -> int:
    if wire == 0:
        _, i = _read_varint(data, i)
    elif wire == 1:
        i += 8
    elif wire == 2:
        ln, i = _read_varint(data, i)
        i += ln
    elif wire == 5:
        i += 4
    else:
        raise ValueError(f"protobuf: unsupported wire type {wire}")
    return i


def _decode_tensor(data: bytes) -> Tuple[str, int, List[int], bytes]:
    name, ty, dims, payload = "", _NNS_END, [], b""
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            ln, i = _read_varint(data, i)
            name = data[i:i + ln].decode()
            i += ln
        elif field == 2 and wire == 0:
            ty, i = _read_varint(data, i)
        elif field == 3 and wire == 2:          # packed dims
            ln, i = _read_varint(data, i)
            end = i + ln
            while i < end:
                d, i = _read_varint(data, i)
                dims.append(d)
        elif field == 3 and wire == 0:          # unpacked dim
            d, i = _read_varint(data, i)
            dims.append(d)
        elif field == 4 and wire == 2:
            ln, i = _read_varint(data, i)
            payload = data[i:i + ln]
            i += ln
        else:
            i = _skip(data, i, wire)
    return name, ty, dims, payload


def protobuf_decode(data: bytes) -> Tuple[Buffer, TensorsSpec]:
    data = bytes(data)
    native = _native_decode(data)
    if native is not None:
        return native
    rate_n = rate_d = 0
    fmt = int(TensorFormat.STATIC.value)
    arrays, names = [], []
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            _, i = _read_varint(data, i)        # num_tensor (len(tensor) wins)
        elif field == 2 and wire == 2:
            ln, i = _read_varint(data, i)
            sub, j = data[i:i + ln], 0
            i += ln
            while j < len(sub):
                k2, j = _read_varint(sub, j)
                f2, w2 = k2 >> 3, k2 & 7
                if f2 == 1 and w2 == 0:
                    rate_n, j = _read_varint(sub, j)
                elif f2 == 2 and w2 == 0:
                    rate_d, j = _read_varint(sub, j)
                else:
                    j = _skip(sub, j, w2)
        elif field == 3 and wire == 2:
            ln, i = _read_varint(data, i)
            nm, ty, dims, payload = _decode_tensor(data[i:i + ln])
            i += ln
            names.append(nm)
            arrays.append(_np_from_wire(ty, dims, payload))
        elif field == 4 and wire == 0:
            fmt, i = _read_varint(data, i)
        else:
            i = _skip(data, i, wire)
    return _rebuild(arrays, names, rate_n, rate_d, fmt)


# -- native (C++) protobuf codec, transparent fast path ----------------------

def _native_encode(arrays, names, rate, fmt):
    import ctypes

    from ..nativelib import RANK_LIMIT, get_native

    lib = get_native()
    if lib is None:
        return None
    n = len(arrays)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    payloads = [np.ascontiguousarray(a) for a in arrays]
    ptrs = (u8p * n)(*[p.ctypes.data_as(u8p) for p in payloads])
    sizes = (ctypes.c_uint64 * n)(*[p.nbytes for p in payloads])
    dtypes = (ctypes.c_uint32 * n)(*[
        int(DType.from_np(a.dtype).value) for a in arrays])
    dims = (ctypes.c_uint32 * (n * RANK_LIMIT))()
    for i, a in enumerate(arrays):
        for d, v in enumerate(_wire_dims(a)):
            dims[i * RANK_LIMIT + d] = int(v)
    name_bytes = [nm.encode() for nm in names]
    name_bufs = [ctypes.create_string_buffer(b, len(b) or 1)
                 for b in name_bytes]
    name_ptrs = (u8p * n)(*[ctypes.cast(b, u8p) for b in name_bufs])
    name_lens = (ctypes.c_uint32 * n)(*[len(b) for b in name_bytes])
    bound = lib.nns_pb_encode_bound(sizes, name_lens, n)
    out = np.empty(int(bound), np.uint8)
    written = lib.nns_pb_encode(
        ptrs, sizes, dtypes, dims, name_ptrs, name_lens, n,
        int(rate.numerator), int(rate.denominator), int(fmt.value),
        out.ctypes.data_as(u8p), bound)
    if not written:
        return None
    return out[:written].tobytes()


_scratch = threading.local()


def _decode_scratch(ctypes, cap, rank):
    s = getattr(_scratch, "pb", None)
    if s is None:
        s = _scratch.pb = (
            (ctypes.c_uint64 * cap)(), (ctypes.c_uint64 * cap)(),
            (ctypes.c_uint32 * cap)(), (ctypes.c_uint32 * (cap * rank))(),
            (ctypes.c_uint64 * cap)(), (ctypes.c_uint64 * cap)(),
            (ctypes.c_int32 * 2)(), ctypes.c_uint32())
    return s


def _native_decode(data: bytes):
    import ctypes

    from ..nativelib import RANK_LIMIT, get_native

    lib = get_native()
    if lib is None:
        return None
    from ..core import TENSOR_COUNT_LIMIT

    u8p = ctypes.POINTER(ctypes.c_uint8)
    cap = TENSOR_COUNT_LIMIT
    p_off, p_len, dtypes, dims, n_off, n_len, rate, fmt = \
        _decode_scratch(ctypes, cap, RANK_LIMIT)
    # zero-copy view of the immutable frame (the C side only reads)
    view = np.frombuffer(data, np.uint8)
    n = lib.nns_pb_decode(
        view.ctypes.data_as(u8p), len(data), cap, p_off, p_len, dtypes,
        dims, n_off, n_len, rate, ctypes.byref(fmt))
    if n < 0:
        return None  # malformed per native parser: python path decides
    arrays, names = [], []
    for i in range(n):
        payload = data[p_off[i]:p_off[i] + p_len[i]]
        ds = [dims[i * RANK_LIMIT + d] for d in range(RANK_LIMIT)]
        arrays.append(_np_from_wire(dtypes[i], ds, payload))
        names.append(data[n_off[i]:n_off[i] + n_len[i]].decode()
                     if n_len[i] else "")
    return _rebuild(arrays, names, rate[0], rate[1], int(fmt.value))

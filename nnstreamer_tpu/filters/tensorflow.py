"""``tensorflow`` filter framework: frozen .pb graphs through XLA.

Parity target: the reference's tensorflow sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_tensorflow.cc — TF C-API session over a frozen
GraphDef).  Here the graph is *imported* (filters/tf_import.py): a
hand-rolled protobuf walk rebuilds the network as one jittable JAX
function, so frozen classifiers and the speech-command graph
(DecodeWav → AudioSpectrogram → Mfcc → convnet) run TPU-resident with
no TF runtime.  DecodeWav becomes a host-side container parse
(:func:`nnstreamer_tpu.filters.tf_import.decode_wav_bytes`); the
jitted graph starts at PCM.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..core import TensorsSpec
from .api import FilterError
from .jax_xla import JaxXlaFilter, ModelDef
from .registry import register_filter


@register_filter
class TensorFlowFilter(JaxXlaFilter):
    NAME = "tensorflow"
    ACCELERATORS = ("tpu", "cpu")

    def _load_file(self, path: str) -> ModelDef:
        ext = os.path.splitext(path)[1].lower()
        if ext != ".pb":
            return super()._load_file(path)
        from .tf_import import TFGraph, build_fn

        try:
            fn, weights, in_shape, in_dtype = build_fn(TFGraph(path))
        except (ValueError, NotImplementedError, IndexError, KeyError,
                struct.error) as e:
            raise FilterError(f"tensorflow: {path}: {e}") from e
        in_spec = None
        if in_shape is not None:
            in_spec = TensorsSpec.from_shapes([in_shape],
                                              np.dtype(in_dtype))
        # weights ride as a params pytree (device-placed), not literals
        return ModelDef(fn, weights, in_spec, name=path)


@register_filter
class TensorFlow2Filter(TensorFlowFilter):
    """Alias (reference registers tensorflow2-savedmodel separately;
    frozen-graph import is the shared core)."""

    NAME = "tensorflow2"

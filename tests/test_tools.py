"""Dev tools: check CLI, pipeline dot dump, model-URI resolution,
custom-filter scaffold generator."""

import json
import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.filters.modeluri import (
    register_model_resolver,
    resolve_model_uri,
    unregister_model_resolver,
)
from nnstreamer_tpu.runtime import Pipeline, parse_launch


class TestCheckCli:
    def test_json_output_lists_inventory(self):
        r = subprocess.run(
            [sys.executable, "-m", "nnstreamer_tpu.check", "--json"],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-500:]
        info = json.loads(r.stdout)
        assert "tensor_filter" in info["elements"]
        assert "jax-xla" in info["filter_frameworks"]
        assert "bounding_boxes" in info["decoders"]
        assert "protobuf" in info["converters"]
        assert info["devices"]


class TestDotDump:
    def test_dot_contains_elements_and_caps(self):
        p = parse_launch("appsrc name=src ! tensor_transform mode=typecast "
                         "option=float32 ! appsink name=out")
        p["src"].spec = TensorsSpec.parse("4", "uint8")
        with p:
            p["src"].push_buffer(Buffer.of(np.zeros(4, np.uint8)))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=30)
            dot = p.to_dot()
        assert "digraph" in dot and '"src"' in dot and '"out"' in dot
        assert "other/tensors" in dot  # negotiated caps on an edge


class TestModelUri:
    def test_custom_scheme_resolution(self):
        register_model_resolver("mlagent",
                                lambda uri: f"/models/{uri.split('/')[-1]}")
        try:
            assert resolve_model_uri("mlagent://model/x/3") == "/models/3"
        finally:
            unregister_model_resolver("mlagent")

    def test_passthrough_and_unknown_scheme(self):
        assert resolve_model_uri("plain_name") == "plain_name"
        assert resolve_model_uri(None) is None
        with pytest.raises(KeyError):
            resolve_model_uri("nosuch://a/b")

    def test_filter_element_resolves_uri(self):
        from nnstreamer_tpu.filters.custom import register_custom_easy

        spec = TensorsSpec.parse("4", "float32")
        register_custom_easy("uri_target", lambda xs: xs,
                             in_spec=spec, out_spec=spec)
        register_model_resolver("testdb", lambda uri: "uri_target")
        try:
            p = parse_launch(
                "appsrc name=src ! tensor_filter framework=custom-easy "
                "model=testdb://models/anything ! appsink name=out")
            p["src"].spec = spec
            with p:
                p["src"].push_buffer(Buffer.of(np.ones(4, np.float32)))
                p["src"].end_of_stream()
                assert p.wait_eos(timeout=30)
                got = p["out"].pull(timeout=1)
            np.testing.assert_array_equal(got.tensors[0].np(),
                                          np.ones(4, np.float32))
        finally:
            unregister_model_resolver("testdb")


class TestScaffoldGenerator:
    def test_python3_scaffold_is_loadable_filter(self, tmp_path):
        r = subprocess.run(
            [sys.executable, "tools/gen_custom_filter.py", "myfilt",
             "--in", "4", "--in-type", "float32",
             "--out", "4", "--out-type", "float32",
             "--dir", str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        script = tmp_path / "myfilt.py"
        assert script.is_file()
        # generated scaffold runs through the python3 filter adapter
        p = parse_launch(
            f"appsrc name=src ! tensor_filter framework=python3 "
            f"model={script} ! appsink name=out")
        p["src"].spec = TensorsSpec.parse("4", "float32")
        with p:
            p["src"].push_buffer(Buffer.of(np.ones(4, np.float32)))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=30)
            got = p["out"].pull(timeout=1)
        assert got is not None

    def test_easy_scaffold_registers(self, tmp_path):
        r = subprocess.run(
            [sys.executable, "tools/gen_custom_filter.py", "ez", "--easy",
             "--in", "4", "--in-type", "float32",
             "--out", "4", "--out-type", "float32",
             "--dir", str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        import importlib.util

        spec = importlib.util.spec_from_file_location("ez", tmp_path / "ez.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.register() == "ez"

"""Observability layer (`nnstreamer_tpu.obs`) tests — ISSUE-4 surface.

Registry concurrency, Prometheus exposition golden, pipeline/pool
collection, the HTTP endpoint, the per-buffer latency tracer (residency
sums ≈ e2e, batching park/dispatch/demux marks, Chrome-trace nesting),
zero-cost hooks when no tracer is attached, `nns-top --once` smoke, and
the satellite fixes riding along (`InvokeStats.snapshot` single-lock
consistency, `latency_to_report` no lock re-entry, log handler dedup +
JSON-lines output).
"""

import io
import json
import logging
import threading
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.obs import REGISTRY, TRACE_META_KEY, LatencyTracer, hooks
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.obs.top import main as top_main
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.utils import log as nns_log
from nnstreamer_tpu.utils.stats import InvokeStats

SHAPE = (4,)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_obs", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_obs")


@pytest.fixture(autouse=True)
def _no_tracer_leak():
    yield
    hooks.detach()


def _pipeline(batch=1, name="obs", timeout_ms=5.0, n=64):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=n + 4)
    q = Queue(name="q", max_size_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_obs",
                       batch=batch, batch_timeout_ms=timeout_ms)
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


def _run(p, src, sink, n=16):
    outs = []
    for i in range(n):
        src.push_buffer(Buffer.of(
            np.full(SHAPE, float(i), np.float32), pts=i))
    for _ in range(n):
        b = sink.pull(timeout=10)
        assert b is not None, f"stalled after {len(outs)}"
        outs.append(b)
    src.end_of_stream()
    assert p.wait_eos(timeout=10)
    return outs


# -- registry: instruments ---------------------------------------------------


def test_counter_concurrent_producers_exact_total():
    reg = MetricsRegistry()
    fam = reg.counter("t_total", "test", labelnames=("worker",))
    shared = fam.labels(worker="all")

    def bump():
        own = fam.labels(worker="all")  # same child via the family map
        for _ in range(5000):
            own.inc()

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert shared.value == 8 * 5000


def test_counter_rejects_negative_and_kind_conflicts():
    reg = MetricsRegistry()
    c = reg.counter("t_c", "c").labels()
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        reg.gauge("t_c", "now a gauge?")
    g = reg.gauge("t_g", "g").labels()
    g.set(5)
    g.dec(2)
    assert g.value == 3
    h = reg.histogram("t_h", "h", buckets=(1.0,))
    with pytest.raises(ValueError):
        h.labels().inc()  # histograms take observe(), not inc()
    with pytest.raises(ValueError):
        reg.histogram("t_h", "h", buckets=(2.0,))  # bucket conflict
    assert reg.histogram("t_h", "h", buckets=(1.0,)) is h


def test_exposition_format_golden():
    """Prometheus text format 0.0.4, byte-exact for a fixed registry."""
    reg = MetricsRegistry()
    c = reg.counter("nns_t_frames_total", "frames seen",
                    labelnames=("pipeline", "element"))
    c.labels(pipeline="p0", element="net").inc(3)
    c.labels(pipeline="p1", element="net").inc()
    reg.gauge("nns_t_depth", "queue depth").labels().set(2.5)
    h = reg.histogram("nns_t_lat_s", "latency", buckets=(0.1, 1.0))
    h.labels().observe(0.05)
    h.labels().observe(0.5)
    h.labels().observe(99.0)
    assert reg.exposition() == (
        "# HELP nns_t_depth queue depth\n"
        "# TYPE nns_t_depth gauge\n"
        "nns_t_depth 2.5\n"
        "# HELP nns_t_frames_total frames seen\n"
        "# TYPE nns_t_frames_total counter\n"
        'nns_t_frames_total{element="net",pipeline="p0"} 3\n'
        'nns_t_frames_total{element="net",pipeline="p1"} 1\n'
        "# HELP nns_t_lat_s latency\n"
        "# TYPE nns_t_lat_s histogram\n"
        'nns_t_lat_s_bucket{le="0.1"} 1\n'
        'nns_t_lat_s_bucket{le="1"} 2\n'
        'nns_t_lat_s_bucket{le="+Inf"} 3\n'
        "nns_t_lat_s_sum 99.55\n"
        "nns_t_lat_s_count 3\n")


def test_label_escaping():
    reg = MetricsRegistry()
    reg.counter("t_esc", "", labelnames=("k",)).labels(k='a"b\\c\nd').inc()
    line = [ln for ln in reg.exposition().splitlines()
            if ln.startswith("t_esc{")][0]
    assert line == 't_esc{k="a\\"b\\\\c\\nd"} 1'


# -- registry: pipeline collection ------------------------------------------


def test_exposition_omits_unknown_sentinels():
    """A filter that has not dispatched yet reports -1 sentinels from
    InvokeStats; the exposition must omit those gauges, not export -1
    as a real data point."""
    p, src, flt, sink = _pipeline(name="obs_sentinel")
    p.start()
    try:
        expo = REGISTRY.exposition()
        assert ('nns_filter_invokes_total{element="net",'
                'pipeline="obs_sentinel"} 0') in expo
        for absent in ("nns_filter_latency_us",
                       "nns_filter_throughput_milli_fps",
                       "nns_filter_dispatch_milli_fps"):
            assert f'{absent}{{element="net",pipeline="obs_sentinel"' \
                not in expo
    finally:
        p.stop()


def test_pipeline_registered_while_playing_only():
    p, src, flt, sink = _pipeline(name="obs_reg")
    p.start()
    try:
        names = [t["pipeline"] for t in REGISTRY.snapshot()["pipelines"]]
        assert "obs_reg" in names
    finally:
        p.stop()
    names = [t["pipeline"] for t in REGISTRY.snapshot()["pipelines"]]
    assert "obs_reg" not in names


def test_snapshot_and_exposition_carry_element_stats():
    p, src, flt, sink = _pipeline(batch=4, name="obs_stats")
    p.start()
    try:
        _run(p, src, sink, n=16)
        snap = REGISTRY.snapshot()
        table = [t for t in snap["pipelines"]
                 if t["pipeline"] == "obs_stats"][0]
        rows = {r["element"]: r for r in table["elements"]}
        assert rows["src"]["stats"]["buffers_out"] == 16
        assert rows["net"]["stats"]["buffers_in"] == 16
        assert "queue" in rows["q"]
        f = rows["net"]["filter"]
        assert f["frames"] == 16 and f["invokes"] <= 16
        assert f["batcher"]["max_batch"] == 4
        expo = REGISTRY.exposition()
        assert ('nns_element_buffers_out_total{element="src",'
                'pipeline="obs_stats"} 16') in expo
        assert "nns_filter_invokes_total" in expo
        assert "nns_batcher_flushes_total" in expo
    finally:
        p.stop()


def test_serve_after_close_starts_fresh_listener():
    reg = MetricsRegistry()
    s1 = reg.serve(port=0)
    p1 = s1.port
    s1.close()
    s2 = reg.serve(port=0)
    try:
        assert s2 is not s1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{s2.port}/metrics", timeout=5) as r:
            r.read()
    finally:
        s2.close()
    assert p1  # first ephemeral port was real


def test_metrics_http_endpoint():
    reg = MetricsRegistry()
    reg.counter("t_http_total", "h").labels().inc(7)
    srv = reg.serve(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "t_http_total 7" in text
        with urllib.request.urlopen(base + "/json", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["metrics"]["t_http_total"]["samples"][0]["value"] == 7
    finally:
        srv.close()


# -- tracer ------------------------------------------------------------------


def test_tracer_residency_sums_to_e2e():
    p, src, flt, sink = _pipeline(name="obs_tr")
    with LatencyTracer(sample_every=1) as tr:
        p.start()
        try:
            _run(p, src, sink, n=8)
        finally:
            p.stop()
    recs = tr.records()
    assert len(recs) == 8
    for r in recs:
        assert r["e2e_s"] > 0
        assert set(r["residency_s"]) == {"src", "q", "net", "out"}
        assert sum(r["residency_s"].values()) == pytest.approx(
            r["e2e_s"], abs=1e-6)
    # pts of the sampled frames came through
    assert sorted(r["pts"] for r in recs) == list(range(8))


def test_tracer_batched_park_dispatch_demux_marks():
    p, src, flt, sink = _pipeline(batch=4, name="obs_trb")
    with LatencyTracer(sample_every=1) as tr:
        p.start()
        try:
            _run(p, src, sink, n=8)
        finally:
            p.stop()
    r = tr.records()[0]
    phases = [ph for _, name, ph in r["marks"] if name == "net"]
    for needed in ("chain-in", "park", "dispatch", "demux"):
        assert needed in phases, r["marks"]
    # park precedes dispatch precedes demux in time
    t = {ph: ts for ts, name, ph in r["marks"] if name == "net"}
    assert t["park"] <= t["dispatch"] <= t["demux"]


def test_tracer_sampling_one_in_n():
    p, src, flt, sink = _pipeline(name="obs_trs")
    with LatencyTracer(sample_every=4) as tr:
        p.start()
        try:
            _run(p, src, sink, n=16)
        finally:
            p.stop()
    assert len(tr.records()) == 4
    s = tr.summary()
    assert s["count"] == 4 and s["e2e_p99_s"] >= s["e2e_p50_s"]


def test_tracer_tee_fanout_finalizes_once():
    """Tee pushes ONE buffer object to every branch; the shared trace
    must close exactly once per sampled frame, not once per sink."""
    from nnstreamer_tpu.runtime import parse_launch

    p = parse_launch(
        "appsrc name=src caps=other/tensors,format=static,num_tensors=1,"
        "dimensions=4,types=float32,framerate=0/1 ! tee name=t "
        "t. ! queue name=q1 ! appsink name=s1 max_buffers=32 "
        "t. ! queue name=q2 ! appsink name=s2 max_buffers=32")
    with LatencyTracer(sample_every=1) as tr:
        p.start()
        try:
            for i in range(6):
                p["src"].push_buffer(Buffer.of(
                    np.full(SHAPE, float(i), np.float32), pts=i))
            for name in ("s1", "s2"):
                for _ in range(6):
                    assert p[name].pull(timeout=10) is not None
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=10)
        finally:
            p.stop()
    assert len(tr.records()) == 6  # one record per frame, not per sink


def test_hooks_are_noops_when_disabled():
    """No tracer attached: buffers carry no trace state and a detached
    tracer receives no callbacks (the hook is one global read)."""

    class Spy(LatencyTracer):
        calls = 0

        def source_created(self, element, buf):
            Spy.calls += 1
            super().source_created(element, buf)

    spy = Spy()
    spy.install()
    spy.uninstall()  # attached then detached BEFORE any traffic
    assert hooks.tracer is None
    p, src, flt, sink = _pipeline(batch=4, name="obs_off")
    p.start()
    try:
        outs = _run(p, src, sink, n=8)
    finally:
        p.stop()
    assert Spy.calls == 0
    for b in outs:
        assert TRACE_META_KEY not in b.meta
        assert b.meta == {}  # no per-buffer allocation at all


def test_chrome_trace_loads_and_nests():
    p, src, flt, sink = _pipeline(batch=4, name="obs_ct")
    with LatencyTracer(sample_every=1) as tr:
        p.start()
        try:
            _run(p, src, sink, n=8)
        finally:
            p.stop()
    doc = json.loads(json.dumps(tr.chrome_trace()))  # JSON round-trip
    events = doc["traceEvents"]
    # complete spans plus the data-movement instant marks (residency
    # flips render as ph="i")
    assert events and all(e["ph"] in ("X", "i") for e in events)
    frames = {e["tid"]: e for e in events if e["cat"] == "frame"}
    assert len(frames) == 8
    eps = 1e-3  # µs jitter tolerance on float math
    for e in events:
        f = frames[e["tid"]]
        assert e["ts"] >= f["ts"] - eps
        assert e["ts"] + e.get("dur", 0) <= f["ts"] + f["dur"] + eps
    # element spans exist for every stage, sub-phases nest inside
    names = {e["name"] for e in events if e["cat"] == "element"}
    assert {"src", "q", "net", "out"} <= names
    sub = {e["name"] for e in events if e["cat"] == "phase"}
    assert "q:queued" in sub and "net:parked" in sub


def test_chrome_trace_saves(tmp_path):
    tr = LatencyTracer()
    path = tmp_path / "trace.json"
    tr.save_chrome_trace(str(path))
    assert json.loads(path.read_text()) == {"traceEvents": [],
                                            "displayTimeUnit": "ms"}


# -- nns-top -----------------------------------------------------------------


def test_nns_top_once_smoke():
    p, src, flt, sink = _pipeline(batch=4, name="obs_top", n=600)
    p.start()
    try:
        stop = threading.Event()

        def feed():
            # 500 < every stage's capacity (n=600): the feeder can
            # never block on a full queue, so join() always returns
            i = 0
            while not stop.is_set() and i < 500:
                src.push_buffer(Buffer.of(
                    np.full(SHAPE, float(i), np.float32), pts=i))
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=feed)
        t.start()
        buf = io.StringIO()
        rc = top_main(["--once", "--interval", "0.25", "--connect", ""],
                      out=buf)
        stop.set()
        t.join()
        text = buf.getvalue()
        assert rc == 0
        assert "pipeline obs_top [PLAYING]" in text
        for col in ("ELEMENT", "OUT/s", "QUEUE", "LAT µs", "DISP/s",
                    "B-OCC"):
            assert col in text
        for el in ("src", "q", "net", "out"):
            assert el in text
        # the queue column renders depth/capacity
        assert "/" in [ln for ln in text.splitlines() if " q " in ln][0]
    finally:
        p.stop()


def test_nns_top_over_http_sees_pool():
    """The acceptance wiring: a share-model pipeline observed over the
    HTTP endpoint shows the POOL row — no bench instrumentation."""
    from nnstreamer_tpu.obs.metrics import serve_metrics
    from nnstreamer_tpu.runtime.serving import MODEL_POOL

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="obs_pool")
    src = AppSrc(name="src", spec=spec, max_buffers=64)
    q = Queue(name="q", max_size_buffers=64)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_obs",
                       batch=4, batch_timeout_ms=2.0, share_model=True)
    sink = AppSink(name="out", max_buffers=64)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    p.start()
    srv = serve_metrics(port=0)
    try:
        _run(p, src, sink, n=8)
        buf = io.StringIO()
        rc = top_main(["--once", "--interval", "0.05",
                       "--connect", f"127.0.0.1:{srv.port}"], out=buf)
        text = buf.getvalue()
        assert rc == 0
        assert "POOL" in text and "jax-xla:_t_obs" in text
        assert "S-OCC" in text
    finally:
        p.stop()
        MODEL_POOL.clear()


def test_nns_top_json_dump():
    buf = io.StringIO()
    rc = top_main(["--json", "--connect", ""], out=buf)
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert "pipelines" in doc and "metrics" in doc


# -- satellites: InvokeStats -------------------------------------------------


def test_invoke_stats_snapshot_consistent_under_concurrent_records():
    """snapshot() reads every derived stat under ONE lock acquisition:
    frames/invokes must divide exactly to the occupancy in the same
    snapshot even while producers hammer record()."""
    st = InvokeStats()
    stop = threading.Event()

    def producer():
        while not stop.is_set():
            st.record(0.001, frames=3, streams=2)

    threads = [threading.Thread(target=producer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            s = st.snapshot()
            if s["invokes"] == 0:
                continue
            assert s["frames"] == 3 * s["invokes"]
            assert s["avg_batch_occupancy"] == pytest.approx(
                s["frames"] / s["invokes"])
            assert s["avg_stream_occupancy"] == pytest.approx(2.0)
    finally:
        stop.set()
        for t in threads:
            t.join()
    s = st.snapshot()
    assert set(s) == {"invokes", "frames", "latency_us",
                      "throughput_milli_fps", "dispatch_milli_fps",
                      "avg_batch_occupancy", "avg_stream_occupancy",
                      "attached_streams", "host_prep_us", "device_us",
                      "host_drain_us", "phase"}


def test_latency_to_report_thresholds():
    st = InvokeStats()
    assert st.latency_to_report() is None
    st.record(0.001)
    first = st.latency_to_report()
    assert first == int(1000 * 1.05)
    assert st.latency_to_report() is None  # unchanged: below threshold
    for _ in range(st._recent.maxlen):
        st.record(0.002)  # window mean doubles: must re-report
    assert st.latency_to_report() == int(2000 * 1.05)


# -- satellites: log ---------------------------------------------------------


def test_log_configure_is_idempotent():
    logger = logging.getLogger("nnstreamer_tpu")

    def ours():
        return [h for h in logger.handlers
                if getattr(h, nns_log._HANDLER_TAG, False)]

    assert len(ours()) == 1
    nns_log.configure()  # re-import / second configure: no stacking
    nns_log.configure()
    assert len(ours()) == 1
    nns_log.configure(force=True)  # force swaps, still exactly one
    assert len(ours()) == 1


def test_log_json_lines_output(monkeypatch):
    monkeypatch.setenv("NNS_TPU_LOG_JSON", "1")
    nns_log.configure(force=True)
    logger = logging.getLogger("nnstreamer_tpu")
    ours = [h for h in logger.handlers
            if getattr(h, nns_log._HANDLER_TAG, False)]
    assert isinstance(ours[0].formatter, nns_log.JsonLineFormatter)
    rec = logger.makeRecord("nnstreamer_tpu", logging.WARNING, "f", 1,
                            "boom %d", (7,), None)
    rec.element = "net"
    doc = json.loads(ours[0].formatter.format(rec))
    assert doc["msg"] == "boom 7"
    assert doc["element"] == "net"  # joins with the metrics label
    assert doc["level"] == "WARNING" and "ts" in doc
    monkeypatch.delenv("NNS_TPU_LOG_JSON")
    nns_log.configure(force=True)  # restore the text handler

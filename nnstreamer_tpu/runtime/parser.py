"""gst-launch-style pipeline description parser.

``parse_launch`` builds a Pipeline from strings like::

    appsrc name=src ! tensor_converter ! tensor_transform mode=typecast
      option=float32 ! tensor_filter framework=jax-xla model=net.pkl !
      tensor_sink name=out

Supported syntax (the subset the reference's pipelines and tests rely on —
see /root/reference/Documentation/gst-launch-script-example.md):
- ``factory prop=value ...`` element segments, ``!`` links
- ``name=...`` names an element; ``somename.`` / ``somename.padname``
  references an existing element (request pads resolved on demand)
- bare caps strings (``other/tensors,format=static,...``) insert an implicit
  capsfilter
- quoted property values via shlex rules
"""

from __future__ import annotations

import shlex
from fractions import Fraction
from typing import List, Optional, Tuple, Union

from ..core import Caps, CapsStruct
from .element import Element, Pad, PadDirection
from .pipeline import Pipeline
from .registry import make, register_element


class ParseError(Exception):
    pass


def parse_caps_string(s: str) -> Caps:
    """Parse ``mime,key=value,...``; values may be ints, fractions, or
    strings; ``{a,b}`` denotes a set."""
    parts = _split_caps_fields(s)
    mime = parts[0].strip()
    fields = {}
    for kv in parts[1:]:
        if "=" not in kv:
            raise ParseError(f"bad caps field {kv!r} in {s!r}")
        k, v = kv.split("=", 1)
        k = k.strip()
        if k in ("dimensions", "types", "format"):
            # grammar fields stay strings: a scalar like dimensions=1 must
            # not become int (it would break the dimensions special-case in
            # caps intersection, which is string-typed)
            fields[k] = v.strip().strip('"')
        else:
            fields[k] = _parse_value(v.strip())
    return Caps.new(CapsStruct.make(mime, **fields))


def _split_caps_fields(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _parse_value(v: str):
    v = v.strip().strip('"')
    if v.startswith("{") and v.endswith("}"):
        return frozenset(_parse_value(x) for x in v[1:-1].split(","))
    if "/" in v:
        a, _, b = v.partition("/")
        if a.strip().lstrip("-").isdigit() and b.strip().isdigit():
            return Fraction(int(a), int(b))
    if v.lstrip("-").isdigit():
        return int(v)
    low = v.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    try:
        return float(v)  # 0.5, 1e-3 — gst-launch float properties
    except ValueError:
        return v


@register_element("capsfilter")
class CapsFilter(Element):
    """Pass-through element that constrains negotiation to its caps."""

    FACTORY = "capsfilter"

    def __init__(self, name=None, caps: Optional[Union[Caps, str]] = None,
                 **props):
        self.caps = caps
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            self.caps = parse_caps_string(self.caps)
        self.add_sink_pad()
        self.add_src_pad()

    def pad_template_caps(self, pad: Pad) -> Caps:
        return self.caps if self.caps is not None else Caps.any_tensors()

    def propose_src_caps(self, pad: Pad) -> Caps:
        base = super().propose_src_caps(pad)
        return base.intersect(self.caps) if self.caps is not None else base

    def chain(self, pad: Pad, buf) -> None:
        self.push(buf)


class _Segment:
    __slots__ = ("kind", "value", "props", "pad")

    def __init__(self, kind, value, props=None, pad=None):
        self.kind = kind  # 'element' | 'ref' | 'caps'
        self.value = value
        self.props = props or {}
        self.pad = pad


def _tokenize(desc: str) -> List[str]:
    lex = shlex.shlex(desc, posix=True)
    lex.whitespace_split = True
    lex.commenters = ""
    return list(lex)


def parse_launch(desc: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    pipe = pipeline or Pipeline()
    tokens = _tokenize(desc)
    if not tokens:
        raise ParseError("empty pipeline description")

    # split into chains at '!' boundaries, building segments
    chains: List[List[_Segment]] = [[]]
    i = 0
    auto_id = [0]

    def new_name(factory: str) -> str:
        while True:
            n = f"{factory}{auto_id[0]}"
            auto_id[0] += 1
            if n not in pipe.elements:
                return n

    while i < len(tokens):
        tok = tokens[i]
        if tok == "!":
            i += 1
            continue
        # gather props until next '!' or end
        props = {}
        j = i + 1
        while j < len(tokens) and tokens[j] != "!":
            if "=" not in tokens[j]:
                break
            k, v = tokens[j].split("=", 1)
            props[k] = _parse_value(v)
            j += 1
        if "/" in tok and "=" not in tok.split(",")[0]:
            seg = _Segment("caps", tok)
        elif tok.endswith(".") or ("." in tok and "=" not in tok):
            el, _, padname = tok.partition(".")
            seg = _Segment("ref", el, pad=padname or None)
        else:
            seg = _Segment("element", tok, props)
        chains[-1].append(seg)
        i = j
        # a segment not followed by '!' starts a new chain
        if i < len(tokens) and tokens[i] != "!":
            chains.append([])
        elif i >= len(tokens):
            break
        else:
            i += 1  # skip '!'

    # instantiate and link
    for chain in chains:
        prev: Optional[Tuple[Element, Optional[str]]] = None
        for seg in chain:
            if seg.kind == "element":
                nm = seg.props.pop("name", None) or new_name(seg.value)
                # config-file applies AFTER the other keys of this
                # segment and never overrides them: explicit
                # pipeline-string values win over the file
                cfg = seg.props.pop("config-file", None) or \
                    seg.props.pop("config_file", None)
                el = make(seg.value, el_name=str(nm), **{
                    k.replace("-", "_"): v for k, v in seg.props.items()})
                if cfg:
                    el.load_config_file(str(cfg), skip=seg.props.keys())
                pipe.add(el)
                cur: Tuple[Element, Optional[str]] = (el, None)
            elif seg.kind == "caps":
                el = CapsFilter(name=new_name("capsfilter"), caps=seg.value)
                pipe.add(el)
                cur = (el, None)
            else:  # ref
                if seg.value not in pipe.elements:
                    raise ParseError(f"unknown element reference {seg.value!r}")
                cur = (pipe.elements[seg.value], seg.pad)
            if prev is not None:
                _link(prev, cur)
            prev = cur
    return pipe


def _link(a: Tuple[Element, Optional[str]], b: Tuple[Element, Optional[str]]
          ) -> None:
    ael, apad = a
    bel, bpad = b
    src = ael.get_pad(apad) if apad else _free_pad(ael, PadDirection.SRC)
    sink = bel.get_pad(bpad) if bpad else _free_pad(bel, PadDirection.SINK)
    src.link(sink)


def _free_pad(el: Element, direction: PadDirection) -> Pad:
    pads = el.srcpads if direction == PadDirection.SRC else el.sinkpads
    for p in pads:
        if p.peer is None:
            return p
    rp = el.request_pad("src_%u" if direction == PadDirection.SRC
                        else "sink_%u")
    if rp is not None:
        return rp
    raise ParseError(f"{el.name}: no free {direction.value} pad")
